"""Bit-packed state: pack/unpack round trips and packed==unpacked parity.

The packed pull path is the bench fast path (bench.py), so its contract is
the strongest we have: bitwise-identical trajectories to the unpacked pull
kernel under the same seeds — single-device AND sharded — plus exact
message accounting and coverage agreement."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gossip_tpu import config as C
from gossip_tpu.config import FaultConfig, ProtocolConfig, RunConfig
from gossip_tpu.models.si import coverage, make_si_round
from gossip_tpu.models.si_packed import (
    init_packed_state, make_packed_round, simulate_until_packed)
from gossip_tpu.models.state import alive_mask, init_state
from gossip_tpu.ops.bitpack import coverage_packed, pack, unpack
from gossip_tpu.parallel.sharded import make_mesh
from gossip_tpu.parallel.sharded_packed import (
    init_sharded_packed_state, make_sharded_packed_round,
    simulate_until_packed_sharded)
from gossip_tpu.topology import generators as G


@pytest.mark.parametrize("r", [1, 3, 32, 33, 100])
def test_pack_unpack_roundtrip(r):
    key = jax.random.key(r)
    seen = jax.random.bernoulli(key, 0.3, (57, r))
    np.testing.assert_array_equal(np.asarray(unpack(pack(seen), r)),
                                  np.asarray(seen))


@pytest.mark.parametrize("r", [1, 31, 64])
def test_coverage_packed_matches_unpacked(r):
    key = jax.random.key(r + 7)
    seen = jax.random.bernoulli(key, 0.4, (200, r))
    alive = jax.random.bernoulli(jax.random.key(1), 0.9, (200,))
    for a in (None, alive):
        cp = float(coverage_packed(pack(seen), r, a))
        cu = float(coverage(seen, a))
        assert cp == pytest.approx(cu, abs=1e-6)


CASES = [
    ("pull-complete", ProtocolConfig(mode=C.PULL, fanout=2, rumors=40),
     lambda: G.complete(96), None),
    ("pull-er-fault", ProtocolConfig(mode=C.PULL, fanout=1, rumors=5),
     lambda: G.erdos_renyi(96, 0.1, seed=3),
     FaultConfig(node_death_rate=0.1, drop_prob=0.2, seed=7)),
    ("antientropy", ProtocolConfig(mode=C.ANTI_ENTROPY, fanout=1, rumors=2,
                                   period=3),
     lambda: G.watts_strogatz(96, 4, 0.2, seed=1), None),
]


@pytest.mark.parametrize("name,proto,topo_fn,fault", CASES,
                         ids=[c[0] for c in CASES])
def test_packed_bitwise_equals_unpacked(name, proto, topo_fn, fault):
    topo = topo_fn()
    run = RunConfig(seed=11)
    rounds = 6
    ustep = jax.jit(make_si_round(proto, topo, fault, run.origin))
    ust = init_state(run, proto, topo.n)
    pstep = jax.jit(make_packed_round(proto, topo, fault, run.origin))
    pst = init_packed_state(run, proto, topo.n)
    for _ in range(rounds):
        ust = ustep(ust)
        pst = pstep(pst)
    np.testing.assert_array_equal(
        np.asarray(unpack(pst.seen, proto.rumors)), np.asarray(ust.seen))
    assert float(pst.msgs) == pytest.approx(float(ust.msgs))


@pytest.mark.parametrize("name,proto,topo_fn,fault", CASES,
                         ids=[c[0] for c in CASES])
def test_sharded_packed_bitwise_parity(name, proto, topo_fn, fault):
    topo = topo_fn()
    run = RunConfig(seed=11)
    mesh = make_mesh(8)
    rounds = 6
    pstep = jax.jit(make_packed_round(proto, topo, fault, run.origin))
    pst = init_packed_state(run, proto, topo.n)
    sstep = jax.jit(make_sharded_packed_round(proto, topo, mesh, fault,
                                              run.origin))
    sst = init_sharded_packed_state(run, proto, topo, mesh)
    for _ in range(rounds):
        pst = pstep(pst)
        sst = sstep(sst)
    np.testing.assert_array_equal(np.asarray(sst.seen)[:topo.n],
                                  np.asarray(pst.seen))
    assert float(sst.msgs) == pytest.approx(float(pst.msgs))


def test_simulate_until_packed_converges():
    proto = ProtocolConfig(mode=C.PULL, fanout=1, rumors=33)
    rounds, cov, msgs, final = simulate_until_packed(
        proto, G.complete(2000), RunConfig(max_rounds=64))
    assert cov >= 0.99
    assert 0 < rounds < 40
    assert msgs > 0
    # sharded twin reaches the same rounds count
    mesh = make_mesh(8)
    r2, cov2, msgs2, _ = simulate_until_packed_sharded(
        proto, G.complete(2000), RunConfig(max_rounds=64), mesh)
    assert r2 == rounds
    assert cov2 == pytest.approx(cov)   # reduction order differs slightly
    assert msgs2 == pytest.approx(msgs)


def test_packed_rejects_push_modes():
    with pytest.raises(ValueError, match="pull/antientropy"):
        make_packed_round(ProtocolConfig(mode=C.PUSH), G.complete(64))
    with pytest.raises(ValueError, match="pull/antientropy"):
        make_sharded_packed_round(ProtocolConfig(mode=C.PUSH_PULL),
                                  G.complete(64), make_mesh(2))
