"""Pallas hardware-PRNG sampler tests.

The TPU interpreter on CPU stubs ``prng_random_bits`` with ZEROS (verified
empirically — seeds are ignored and every draw is 0), so interpret-mode
tests can only exercise the kernel's mechanics: shapes, grid/blocking,
range mapping, and the self-exclusion shift.  The statistical contracts
(seed sensitivity, uniformity) are TPU-only tests; the driver's bench run
exercises them on hardware, and `GOSSIP_TPU_TEST_PLATFORM=tpu pytest`
runs them on a real chip.
"""

import jax
import numpy as np
import pytest

from gossip_tpu.ops.pallas_sampling import (round_seed,
                                            sample_targets_pallas)

ON_TPU = jax.default_backend() == "tpu"


def sample(seed, rows, n, k=1, excl=True):
    import jax.numpy as jnp
    return np.asarray(sample_targets_pallas(jnp.int32(seed), rows, n, k,
                                            excl, interpret=not ON_TPU))


def test_range_and_shape():
    t = sample(7, 1000, 5000, k=3)
    assert t.shape == (1000, 3)
    assert t.min() >= 0 and t.max() < 5000


def test_deterministic():
    a = sample(42, 500, 10_000)
    b = sample(42, 500, 10_000)
    np.testing.assert_array_equal(a, b)


def test_exclude_self():
    # In interpret mode all bits are zero, so every draw is 0 and the shift
    # trick must bump row 0's draw to 1; on TPU this covers real draws.
    t = sample(3, 4096, 4096, k=4, excl=True)
    rows = np.arange(4096)[:, None]
    assert (t != rows).all()


def test_round_seed_folding():
    import jax.numpy as jnp
    s1 = round_seed(5, jnp.int32(0))
    s2 = round_seed(5, jnp.int32(1))
    s3 = round_seed(6, jnp.int32(0))
    assert len({int(s1), int(s2), int(s3)}) == 3


@pytest.mark.skipif(not ON_TPU, reason="CPU interpreter stubs the PRNG "
                    "with zeros; statistics need a real chip")
class TestOnTpu:
    def test_seed_varies_stream(self):
        a = sample(42, 500, 10_000)
        c = sample(43, 500, 10_000)
        assert (a != c).any()

    def test_blocks_vary(self):
        # blocks must not repeat each other's stream
        t = sample(9, 8192, 1 << 30, k=1, excl=False)[:, 0]
        assert (t[:4096] != t[4096:]).any()

    def test_uniformity_chi_square(self):
        n, buckets = 64, 16
        t = sample(11, 8192, n, k=1, excl=False)[:, 0]
        counts = np.bincount(t * buckets // n, minlength=buckets)
        expected = len(t) / buckets
        chi2 = ((counts - expected) ** 2 / expected).sum()
        assert chi2 < 60, counts
