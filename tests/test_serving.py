"""Admission-batched serving (rpc/batcher + parallel/sweep
.request_sweep_curves + tools/load_harness): megabatch-vs-solo bitwise
equality, compile-count pins, sidecar coalescing/deadline/backpressure/
error-hygiene contracts, and the committed serving record's gates."""

import hashlib
import json
import os
import threading
import time

import numpy as np
import pytest

from gossip_tpu.config import (ChurnConfig, FaultConfig, ProtocolConfig,
                               RunConfig, ServingConfig)
from gossip_tpu.parallel.sweep import RequestSpec, request_sweep_curves
from gossip_tpu.utils import telemetry

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SERVING_RECORD = os.path.join(_REPO, "artifacts",
                              "ledger_serving_r14.jsonl")


def _mixed_specs(salt=0):
    """The canonical mixed megabatch: four modes, static fault, churn
    schedule, mixed n within one pow2 bucket, mixed rumor counts,
    distinct seeds/targets.  ``salt`` varies CONTENT only (seeds,
    schedule node ids, targets) at the SAME per-request shapes — a
    salted batch re-enters the compiled scan AND every eager
    mask-builder shape, so the repeat pin can demand zero compiles."""
    run10 = lambda **kw: RunConfig(max_rounds=10, **kw)  # noqa: E731
    return (
        RequestSpec(ProtocolConfig(mode="pushpull", fanout=2),
                    run10(seed=1 + salt), None, 500),
        RequestSpec(ProtocolConfig(mode="pull", fanout=2),
                    run10(seed=2 + salt),
                    FaultConfig(node_death_rate=0.1, drop_prob=0.1,
                                seed=5 + salt), 300),
        RequestSpec(ProtocolConfig(mode="antientropy", fanout=2,
                                   period=2),
                    run10(seed=3 + salt, target_coverage=0.9),
                    FaultConfig(drop_prob=0.2, seed=1), 500),
        RequestSpec(ProtocolConfig(mode="pushpull", fanout=2, rumors=2),
                    run10(seed=3),
                    FaultConfig(drop_prob=0.05, seed=5,
                                churn=ChurnConfig(
                                    events=((3 + salt, 1, 4),
                                            (7, 2, -1)),
                                    partitions=((1, 3, 250),),
                                    ramp=(0, 2, 0.0, 0.2))), 500),
        RequestSpec(ProtocolConfig(mode="pull", fanout=2, rumors=3),
                    run10(seed=7 + salt), None, 200),
    )


def _solo_digest(state):
    return hashlib.sha256(np.ascontiguousarray(
        np.asarray(state.seen)).tobytes()).hexdigest()


def _assert_solo_parity(res, specs, members):
    from gossip_tpu.runtime.simulator import simulate_curve
    from gossip_tpu.topology import generators as G
    for i in members:
        sp = specs[i]
        solo = simulate_curve(sp.proto, G.complete(sp.n), sp.run,
                              sp.fault)
        assert np.array_equal(res.curves[i],
                              np.asarray(solo.coverage)), sp
        assert np.array_equal(res.msgs[i], np.asarray(solo.msgs)), sp
        assert int(res.rounds_to_target[i]) == solo.rounds_to_target
        assert res.state_digests[i] == _solo_digest(solo.state), sp


def test_request_megabatch_matches_solo_dispatch_bitwise():
    """THE serving tentpole contract: every request in a mixed
    megabatch — modes, faults, a churn schedule, mixed n and rumor
    counts in one bucket — returns exactly the bytes its solo
    simulate_curve dispatch returns: coverage curve, cumulative msgs,
    rounds-to-target, and the final-state sha256 digest.  (The host
    readout emulates the solo division lowering per request —
    docs/SERVING.md bitwise-contract section.)  In-gate: the two
    readout classes — unweighted (no fault) and weighted (the churn
    member, the hardest lowering: schedule + cut + lost accounting);
    each solo reference is a full fresh compile (~4 s), so the static-
    fault / AE / mixed-rumor members ride the slow twin below."""
    specs = _mixed_specs(0)
    res = request_sweep_curves(specs)
    _assert_solo_parity(res, specs, (0, 3))
    # the per-request rows split back out of the stacked buffers agree
    rows = res.metrics_rows()
    assert [r["mode"] for r in rows] == [sp.proto.mode for sp in specs]
    assert rows[3]["dropped_total"] > 0       # the churn request lost
    assert all(r["dropped"][0] >= 0 for r in rows)


@pytest.mark.slow
def test_request_megabatch_matches_solo_dispatch_all_members():
    specs = _mixed_specs(0)
    res = request_sweep_curves(specs)
    _assert_solo_parity(res, specs, range(len(specs)))


def test_request_megabatch_compiles_once_and_reuses(assert_compiles):
    """K compatible requests compile ONE scan, and a DIFFERENT request
    mix of the same bucket shapes re-enters the executable with ZERO
    backend compiles — steady-state serving never touches the compile
    path (the _cached_request_sweep_scan memo contract)."""
    base = request_sweep_curves(_mixed_specs(0))   # warm (shared with
    #                                       the bitwise test's shapes)
    with assert_compiles(0):
        salted = request_sweep_curves(_mixed_specs(1))
    # content actually changed: different trajectories, same shapes
    assert not np.array_equal(base.curves[0], salted.curves[0])


# The IN-GATE composition-invariance smoke lives in
# test_sidecar_coalesces_concurrent_requests_bitwise below: each RPC
# reply is compared against its K=1 driver dispatch at the tick's lane
# bucket (a warm executable).  The driver-level all-members depth —
# whose K=1 lane-1 dispatches each compile a fresh scan — is slow-tier.

def _assert_member_invariant(specs, batch, i, **kw):
    solo = request_sweep_curves([specs[i]], n_pad=512,  # batch bucket
                                **kw)
    assert np.array_equal(solo.curves[0], batch.curves[i])
    assert np.array_equal(solo.msgs[0], batch.msgs[i])
    assert np.array_equal(solo.dropped[0], batch.dropped[i])
    assert solo.state_digests[0] == batch.state_digests[i]


# depth tier (tier-1 wall budget): each K=1 dispatch at lane count 1
# compiles a fresh scan (~20 s on this host); the in-gate coalesce
# test pins the same property through RPC at warm lane buckets
@pytest.mark.slow
def test_request_batch_composition_invariance_all_members():
    specs = _mixed_specs(0)
    batch = request_sweep_curves(specs)
    for i in range(len(specs)):
        _assert_member_invariant(specs, batch, i)


# --- Mesh-sharded dispatch (ServingConfig.devices — the mesh PR) ----
#
# The batcher's megabatch rides the replica's request-axis mesh; every
# PR 9 contract must survive the sharding bitwise: solo parity,
# composition invariance (padded requests are inert rows), zero
# steady-state compiles.  tests/conftest.py pins 8 XLA host devices,
# so the 4-wide mesh runs inside tier-1.


def _mesh_batcher(devices=4):
    import jax
    if len(jax.devices()) < devices:
        pytest.skip(f"needs {devices} host devices")
    from gossip_tpu.rpc.batcher import Batcher
    return Batcher(ServingConfig(tick_ms=60_000.0, max_batch=64,
                                 devices=devices))


def _mesh_requests(salt=0):
    """Request-dict twin of ``_mixed_specs``' first four members —
    the canonical shapes whose solo readout lowerings the megabatch's
    host readout emulates bitwise (the churn member keeps its
    canonical rumors=2: the weighted-lowering emulation is MEASURED
    against these specs; a different rumor width lands on the other
    side of the recip-mul-vs-true-division lottery docs/SERVING.md
    describes).  The batcher's rumor bucket splits the tick into a
    size-3 rumors=1 megabatch and a size-1 rumors=2 one — BOTH
    dispatched on the mesh with lane buckets floored at the device
    count, so the solo-shaped group exercises the inert-padding
    contract live.  ``salt`` varies content at the same shapes (the
    zero-compile re-entry contract)."""
    return [
        {"proto": {"mode": "pushpull", "fanout": 2},
         "topology": {"family": "complete", "n": 500},
         "run": {"max_rounds": 10, "seed": 1 + salt, "engine": "xla"},
         "curve": True},
        {"proto": {"mode": "pull", "fanout": 2},
         "topology": {"family": "complete", "n": 300},
         "run": {"max_rounds": 10, "seed": 2 + salt, "engine": "xla"},
         "fault": {"node_death_rate": 0.1, "drop_prob": 0.1,
                   "seed": 5 + salt},
         "curve": True},
        {"proto": {"mode": "antientropy", "fanout": 2, "period": 2},
         "topology": {"family": "complete", "n": 500},
         "run": {"max_rounds": 10, "seed": 3 + salt,
                 "target_coverage": 0.9, "engine": "xla"},
         "fault": {"drop_prob": 0.2, "seed": 1},
         "curve": True},
        {"proto": {"mode": "pushpull", "fanout": 2, "rumors": 2},
         "topology": {"family": "complete", "n": 500},
         "run": {"max_rounds": 10, "seed": 3, "engine": "xla"},
         "fault": {"drop_prob": 0.05, "seed": 5,
                   "churn": {"events": [[3 + salt, 1, 4], [7, 2, -1]],
                             "partitions": [[1, 3, 250]],
                             "ramp": [0, 2, 0.0, 0.2]}},
         "curve": True},
    ]


def _mesh_tick(batcher, reqs):
    """Submit ``reqs`` and drain ONE tick deterministically (tick_ms
    is far beyond the test wall, so the collector thread never races
    the explicit drain)."""
    from gossip_tpu.backend import request_to_args
    pend = []
    for r in reqs:
        p, why = batcher.submit_run(request_to_args(r), None)
        assert p is not None, why
        pend.append(p)
    batcher._drain_once()
    return [p.wait() for p in pend]


def _assert_reply_solo_parity(reply, req):
    from gossip_tpu.backend import request_to_args
    from gossip_tpu.rpc.batcher import classify_run
    from gossip_tpu.runtime.simulator import simulate_curve
    from gossip_tpu.topology import generators as G
    _, sp, _ = classify_run(request_to_args(req))
    solo = simulate_curve(sp.proto, G.complete(sp.n), sp.run, sp.fault)
    assert np.array_equal(np.asarray(reply["curve"]),
                          np.asarray(solo.coverage)), req
    assert reply["msgs"] == float(np.asarray(solo.msgs)[-1]), req
    assert reply["rounds"] == solo.rounds_to_target, req
    assert reply["meta"]["state_digest"] == _solo_digest(solo.state)


def test_mesh_batcher_matches_solo_dispatch_bitwise():
    """THE mesh tentpole contract: a mixed megabatch dispatched over
    the replica's 4-device request mesh returns, per request, exactly
    the bytes its solo simulate_curve dispatch returns — curve, msgs,
    rounds, final-state digest.  In-gate members: the unweighted
    readout and the churn member (weighted readout — the hardest
    lowering); the full sweep rides the slow twin."""
    b = _mesh_batcher()
    try:
        reqs = _mesh_requests(0)
        replies = _mesh_tick(b, reqs)
        # one tick, two mesh megabatches: the rumor bucket splits the
        # mix (rumors=1 x3, rumors=2 x1) and BOTH groups ride the
        # 4-device mesh — the size-1 group at 4 lanes, three of them
        # inert padding
        assert all(r["meta"]["devices"] == 4 for r in replies)
        assert all(r["meta"]["batch"]["size"] == 3 for r in replies[:3])
        assert replies[3]["meta"]["batch"]["size"] == 1
        for i in (0, 3):
            _assert_reply_solo_parity(replies[i], reqs[i])
    finally:
        b.close()


@pytest.mark.slow
def test_mesh_batcher_matches_solo_dispatch_all_members():
    b = _mesh_batcher()
    try:
        reqs = _mesh_requests(0)
        replies = _mesh_tick(b, reqs)
        for i in range(len(reqs)):
            _assert_reply_solo_parity(replies[i], reqs[i])
    finally:
        b.close()


def test_mesh_batcher_zero_compiles_on_salted_reentry(assert_compiles):
    """A DIFFERENT request mix of the same bucket shapes re-enters the
    mesh executable with ZERO backend compiles — mesh dispatch must
    not fragment the cache (one mesh per batcher lifetime, pow2 lane
    buckets floored at the device count)."""
    b = _mesh_batcher()
    try:
        base = _mesh_tick(b, _mesh_requests(0))        # warm
        with assert_compiles(0):
            salted = _mesh_tick(b, _mesh_requests(1))
        # content actually changed: same shapes, different trajectories
        assert base[0]["curve"] != salted[0]["curve"]
        assert all(r["meta"]["batch"]["cache"] == "warm"
                   for r in salted)
    finally:
        b.close()


def test_mesh_batch_composition_invariance_inert_padding():
    """Driver-level mesh invariance: a member's rows in a full mesh
    megabatch equal its K=1 dispatch on the SAME mesh — where 7 of the
    8 bucket lanes are padding — so padded requests provably ride
    inert rows (the fixed-concurrency capture depends on it: partial
    last ticks shard the same executable)."""
    import jax
    if len(jax.devices()) < 4:
        pytest.skip("needs 4 host devices")
    from jax.sharding import Mesh
    mesh = Mesh(jax.devices()[:4], ("request",))
    specs = _mixed_specs(0)
    batch = request_sweep_curves(specs, mesh=mesh, lanes=8, full=True)
    for i in (0, 3):
        solo = request_sweep_curves([specs[i]], n_pad=512, mesh=mesh,
                                    lanes=8, full=True)
        assert np.array_equal(solo.curves[0], batch.curves[i])
        assert np.array_equal(solo.msgs[0], batch.msgs[i])
        assert np.array_equal(solo.dropped[0], batch.dropped[i])
        assert solo.state_digests[0] == batch.state_digests[i]


def test_mesh_config_refuses_bad_widths():
    """ServingConfig.devices must be a pow2 (lane buckets divide the
    mesh) and the Batcher must refuse a mesh wider than the process's
    devices — the silent-degradation failure the fleet gate exists
    for."""
    with pytest.raises(ValueError, match="power of two"):
        ServingConfig(devices=3)
    import jax
    from gossip_tpu.rpc.batcher import Batcher
    too_many = max(16, len(jax.devices()) * 2)
    with pytest.raises(ValueError, match="silently degrade"):
        Batcher(ServingConfig(devices=too_many))


def test_request_sweep_validation():
    spec = _mixed_specs(0)[0]
    import dataclasses
    with pytest.raises(ValueError, match="fanouts"):
        request_sweep_curves([spec, dataclasses.replace(
            spec, proto=ProtocolConfig(mode="pull", fanout=3))])
    with pytest.raises(ValueError, match="max_rounds"):
        request_sweep_curves([spec, dataclasses.replace(
            spec, run=RunConfig(max_rounds=20))])
    with pytest.raises(ValueError, match="flood|round structure"):
        RequestSpec(ProtocolConfig(mode="flood", fanout=2),
                    RunConfig(), None, 64)
    with pytest.raises(ValueError, match="anti-entropy"):
        RequestSpec(ProtocolConfig(mode="pull", fanout=2, period=3),
                    RunConfig(), None, 64)
    with pytest.raises(ValueError, match="n >= 2"):
        RequestSpec(ProtocolConfig(mode="pull", fanout=2),
                    RunConfig(), None, 1)


def test_classify_run_reasons():
    """The batch-key derivation: compatible requests key together,
    incompatible ones fall through with a NAMED reason (the loud
    label)."""
    from gossip_tpu.backend import request_to_args
    from gossip_tpu.rpc.batcher import classify_run
    base = {"backend": "jax-tpu",
            "proto": {"mode": "pull", "fanout": 2},
            "topology": {"family": "complete", "n": 300},
            "run": {"max_rounds": 8}}
    key, spec, want_curve = classify_run(request_to_args(dict(base)))
    assert key is not None and key.n_bucket == 512
    # same bucket, different n / mode / drop / seed -> SAME key
    other = {**base, "proto": {"mode": "pushpull", "fanout": 2},
             "topology": {"family": "complete", "n": 500},
             "fault": {"drop_prob": 0.2},
             "run": {"max_rounds": 8, "seed": 9}}
    key2, _, _ = classify_run(request_to_args(other))
    assert key2 == key
    for patch, why in (
            ({"backend": "go-native"}, "backend"),
            ({"proto": {"mode": "rumor"}}, "mode"),
            ({"run": {"engine": "fused"}}, "engine"),
            ({"mesh": {"n_devices": 2}}, "mesh"),
            ({"fault": {"dead_nodes": [1]}}, "swim"),
            # per-request content validation at CLASSIFY time: an
            # out-of-range churn event falls through to the solo
            # path's INVALID_ARGUMENT instead of poisoning a megabatch
            ({"fault": {"churn": {"events": [[999, 1, 3]]}}},
             "node ids"),
    ):
        bad = {**base, **patch}
        k, reason, _ = classify_run(request_to_args(bad))
        assert k is None and why in reason, (patch, reason)
    # engine='auto' requests that the solo path would route to the
    # fused TPU engine must fall through (the bitwise contract) —
    # never true on this CPU tier, so pin the consult via monkeypatch
    import gossip_tpu.backend as backend_mod
    orig = backend_mod._fused_auto_ok
    backend_mod._fused_auto_ok = lambda *a: True
    try:
        k, reason, _ = classify_run(request_to_args(dict(base)))
        assert k is None and "fused" in reason
    finally:
        backend_mod._fused_auto_ok = orig
    # ...and on CPU (fused ineligible) auto requests batch normally
    k, _, _ = classify_run(request_to_args(dict(base)))
    assert k is not None
    # different fanout / rounds / rumor bucket -> DIFFERENT key
    k3, _, _ = classify_run(request_to_args(
        {**base, "proto": {"mode": "pull", "fanout": 3}}))
    k4, _, _ = classify_run(request_to_args(
        {**base, "run": {"max_rounds": 16}}))
    assert k3 != key and k4 != key
    # ensemble admission: one lane per seed, same key as Run requests
    from gossip_tpu.rpc.batcher import classify_ensemble
    ekey, especs = classify_ensemble(request_to_args(dict(base)),
                                     None, 3)
    assert ekey == key and len(especs) == 3
    assert [s.run.seed for s in especs] == [0, 1, 2]
    ekey2, reason = classify_ensemble(request_to_args(
        {**base, "proto": {"mode": "rumor"}}), None, 3)
    assert ekey2 is None and "mode" in reason


# -- sidecar integration ----------------------------------------------

def _serve_batching(**kw):
    grpc = pytest.importorskip("grpc")  # noqa: F841
    from gossip_tpu.rpc.sidecar import serve
    cfg = ServingConfig(**{"tick_ms": 150, "max_batch": 16, **kw})
    return serve(port=0, max_workers=8, batching=cfg)


def test_sidecar_coalesces_concurrent_requests_bitwise():
    """The in-gate LIVE batch: concurrent mixed-mode RPCs coalesce into
    one megabatch (meta.batch.size > 1), each reply's payload equals
    its request's direct driver dispatch byte for byte (and therefore,
    by the solo-parity + composition pins above, its solo
    simulate_curve dispatch), and a non-batchable request falls
    through loudly labeled.  References run through the SAME warm
    executable (same bucket + lane count), so this test compiles one
    scan, not one per request."""
    from gossip_tpu.backend import request_to_args
    from gossip_tpu.rpc.batcher import classify_run
    from gossip_tpu.rpc.sidecar import SidecarClient
    # a long tick so all three concurrent submissions land in ONE
    # collector drain deterministically (the size == 3 assertion)
    server, port = _serve_batching(tick_ms=400)
    try:
        client = SidecarClient(f"127.0.0.1:{port}")
        reqs = [dict(backend="jax-tpu", proto={"mode": m, "fanout": 2},
                     topology={"family": "complete", "n": 300},
                     run={"max_rounds": 8, "seed": s, "engine": "xla"},
                     curve=True)
                for m, s in (("pushpull", 1), ("pull", 2),
                             ("push", 3))]
        specs = [classify_run(request_to_args(dict(r)))[1]
                 for r in reqs]
        out = [None] * len(reqs)

        def fire(i):
            out[i] = client.run(timeout=300, **reqs[i])
        threads = [threading.Thread(target=fire, args=(i,))
                   for i in range(len(reqs))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i, rep in enumerate(out):
            b = rep["meta"]["batch"]
            assert b["batched"] is True
            assert b["size"] == len(reqs)       # one megabatch tick
            assert b["semantics"] == "fixed-scan"
            # the reference rides the same warm executable: K=1 padded
            # to the tick's lane bucket (composition invariance)
            ref = request_sweep_curves([specs[i]], n_pad=512,
                                       lanes=4, full=True)
            assert rep["curve"] == [float(c) for c in ref.curves[0]]
            assert rep["msgs"] == float(ref.msgs[0][-1])
            assert rep["coverage"] == float(ref.curves[0][-1])
            assert rep["rounds"] == int(ref.rounds_to_target[0])
            assert rep["meta"]["state_digest"] == ref.state_digests[0]
        # non-batchable request: solo fallthrough, loudly labeled
        # (go-native: cheap, no jax compile behind it)
        rep = client.run(timeout=300, backend="go-native",
                         proto={"mode": "flood", "fanout": 1},
                         topology={"family": "ring", "n": 32, "k": 2},
                         run={"max_rounds": 16})
        assert rep["meta"]["batch"]["batched"] is False
        assert "go-native" in rep["meta"]["batch"]["reason"]
        client.close()
    finally:
        server.gossip_batcher.close()
        server.stop(grace=None)


# depth tier (tier-1 wall budget): the solo run_ensemble reference
# compiles its own vmapped scan (~30 s); the in-gate coalesce test
# keeps the Ensemble surface's admission covered via classify, and the
# driver-level solo parity chain covers the per-seed trajectories
@pytest.mark.slow
def test_sidecar_batched_ensemble_matches_solo():
    """A batched Ensemble RPC (per-seed megabatch lanes) returns
    exactly the solo run_ensemble summary."""
    from gossip_tpu.backend import request_to_args, run_ensemble
    from gossip_tpu.rpc.sidecar import SidecarClient
    server, port = _serve_batching(tick_ms=100)
    try:
        client = SidecarClient(f"127.0.0.1:{port}")
        ens_req = dict(backend="jax-tpu",
                       proto={"mode": "pull", "fanout": 2},
                       topology={"family": "complete", "n": 300},
                       run={"max_rounds": 8, "engine": "xla"})
        batched = client.ensemble(timeout=300, ensemble=4, **ens_req)
        assert batched["batch"]["batched"] is True
        assert batched["batch"]["size"] == 4        # one lane per seed
        args = request_to_args(dict(ens_req))
        ens, _ = run_ensemble(proto=args["proto"], tc=args["tc"],
                              run=args["run"], fault=None, count=4)
        assert batched["ensemble"] == ens.summary()
        client.close()
    finally:
        server.gossip_batcher.close()
        server.stop(grace=None)


def test_sidecar_error_hygiene_one_line_no_retry(tmp_path):
    """Satellite pin: malformed JSON / unknown fields / non-object
    payloads are INVALID_ARGUMENT with a ONE-LINE message (never a
    stringified traceback), and SidecarClient raises them immediately
    — zero retries (no rpc_retry events on the ambient ledger)."""
    grpc = pytest.importorskip("grpc")
    from gossip_tpu.rpc.sidecar import SidecarClient, serve
    server, port = serve(port=0, max_workers=2)
    led_path = str(tmp_path / "client.jsonl")
    try:
        client = SidecarClient(f"127.0.0.1:{port}")
        led = telemetry.Ledger(led_path)
        prev = telemetry.activate(led)
        try:
            for payload in (b'{"nope', b'[1, 2]', b'"hi"',
                            json.dumps({"proto": {"fanoot": 2}})
                            .encode(),
                            json.dumps({"proto": "x"}).encode()):
                t0 = time.monotonic()
                with pytest.raises(grpc.RpcError) as ei:
                    client._call_with_retry(client._run, payload,
                                            30, "run")
                assert ei.value.code() \
                    == grpc.StatusCode.INVALID_ARGUMENT, payload
                details = ei.value.details()
                assert "\n" not in details
                assert "Traceback" not in details
                # immediate raise: no backoff sleeps happened
                assert time.monotonic() - t0 < 2.0
        finally:
            telemetry.activate(prev)
            led.close()
        events = telemetry.load_ledger(led_path)
        assert not [e for e in events if e.get("ev") == "rpc_retry"]
        client.close()
    finally:
        server.stop(grace=None)
    # the BATCHED ensemble path shares the same one-line net: a
    # malformed seed value must be INVALID_ARGUMENT, never an uncaught
    # int() failure deep in the batcher (review pin)
    bserver, bport = _serve_batching(tick_ms=50)
    try:
        from gossip_tpu.rpc.sidecar import SidecarClient as SC
        bclient = SC(f"127.0.0.1:{bport}")
        with pytest.raises(grpc.RpcError) as ei:
            bclient.ensemble(timeout=30, seeds=["abc"],
                             backend="jax-tpu",
                             proto={"mode": "pull", "fanout": 1},
                             topology={"family": "complete", "n": 8},
                             run={"max_rounds": 2})
        assert ei.value.code() == grpc.StatusCode.INVALID_ARGUMENT
        assert "\n" not in ei.value.details()
        bclient.close()
    finally:
        bserver.gossip_batcher.close()
        bserver.stop(grace=None)


def test_batcher_deadline_and_backpressure(tmp_path):
    """Satellite pins, unit level: (a) a request admitted but expired
    before its tick is rejected with the Expired error and LEDGERED,
    never run late; (b) an admission past the queue cap raises
    QueueFull immediately (backpressure)."""
    from gossip_tpu.backend import request_to_args
    from gossip_tpu.rpc import batcher as B
    args = request_to_args({
        "backend": "jax-tpu", "proto": {"mode": "pull", "fanout": 1},
        "topology": {"family": "complete", "n": 8},
        "run": {"max_rounds": 2}})
    led_path = str(tmp_path / "batcher.jsonl")
    led = telemetry.Ledger(led_path)
    prev = telemetry.activate(led)
    b = B.Batcher(ServingConfig(tick_ms=40, max_batch=8, max_queue=2))
    try:
        # (a) deadline already passed at admission -> expired at tick
        pending, note = b.submit_run(args, time.monotonic() - 0.01)
        assert pending is not None and note is None
        with pytest.raises(B.Expired, match="deadline expired"):
            pending.wait()
        # (b) backpressure: fill the 2-lane queue with expired
        # requests (they never run), then the third admission refuses
        b2 = B.Batcher(ServingConfig(tick_ms=10_000, max_batch=8,
                                     max_queue=2))
        try:
            past = time.monotonic() - 0.01
            b2.submit_run(args, past)
            b2.submit_run(args, past)
            with pytest.raises(B.QueueFull, match="queue full"):
                b2.submit_run(args, None)
        finally:
            b2.close()
    finally:
        b.close()
        telemetry.activate(prev)
        led.close()
    events = telemetry.load_ledger(led_path)
    assert [e for e in events if e.get("ev") == "deadline_exceeded"]
    assert [e for e in events if e.get("ev") == "backpressure"]


def test_batcher_rejects_oversized_and_purges_failed_leftovers(
        tmp_path, monkeypatch):
    """Review pins: (a) a request needing more lanes than max_batch is
    refused AT ADMISSION (TooLarge -> INVALID_ARGUMENT) — it could
    never be scheduled and would hang its handler forever; (b) when a
    collector tick dies outside the per-group handling, re-queued
    leftovers are failed AND purged, never re-executed for handlers
    that already aborted."""
    from gossip_tpu.backend import request_to_args
    from gossip_tpu.rpc import batcher as B
    args = request_to_args({
        "backend": "jax-tpu", "proto": {"mode": "pull", "fanout": 1},
        "topology": {"family": "complete", "n": 8},
        "run": {"max_rounds": 2}})
    b = B.Batcher(ServingConfig(tick_ms=10_000, max_batch=4,
                                max_queue=64))
    try:
        with pytest.raises(B.TooLarge, match="megabatch lanes"):
            b.submit_ensemble(args, None, 8, None)
    finally:
        b.close()
    # a CLOSED batcher refuses admission (no collector will ever
    # drain again) instead of stranding the handler thread
    with pytest.raises(B.Closed, match="shut down"):
        b.submit_run(args, None)
    # (b): three 1-lane requests, max_batch 2 -> the third defers to
    # the leftovers; a tick whose group execution BLOWS UP (bug-class
    # failure, monkeypatched) must fail all three and leave the queue
    # EMPTY
    b2 = B.Batcher(ServingConfig(tick_ms=10_000, max_batch=2,
                                 max_queue=64))
    try:
        monkeypatch.setattr(
            B.Batcher, "_run_group",
            lambda self, *a, **k: (_ for _ in ()).throw(
                RuntimeError("boom")))
        pendings = [b2.submit_run(args, None)[0] for _ in range(3)]
        b2._drain_once()
        for p in pendings:
            with pytest.raises(B.BatchError, match="collector tick"):
                p.wait()
        assert b2._queue == []
    finally:
        b2.close()


def test_client_timeout_bounds_queue_wait(tmp_path):
    """RPC-level deadline propagation: a client timeout shorter than
    the collector tick expires IN THE QUEUE — the client sees
    DEADLINE_EXCEEDED (and never retries it for run), and the server
    ledgers the expiry instead of running the request late."""
    grpc = pytest.importorskip("grpc")
    from gossip_tpu.rpc.sidecar import SidecarClient
    led_path = str(tmp_path / "server.jsonl")
    led = telemetry.Ledger(led_path)
    prev = telemetry.activate(led)
    server, port = _serve_batching(tick_ms=400)
    try:
        client = SidecarClient(f"127.0.0.1:{port}")
        with pytest.raises(grpc.RpcError) as ei:
            client.run(timeout=0.08, backend="jax-tpu",
                       proto={"mode": "pull", "fanout": 1},
                       topology={"family": "complete", "n": 8},
                       run={"max_rounds": 2})
        assert ei.value.code() == grpc.StatusCode.DEADLINE_EXCEEDED
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline:
            events = telemetry.load_ledger(led_path)
            if any(e.get("ev") == "deadline_exceeded" for e in events):
                break
            time.sleep(0.05)
        else:
            raise AssertionError("server never ledgered the expiry")
        client.close()
    finally:
        server.gossip_batcher.close()
        server.stop(grace=None)
        telemetry.activate(prev)
        led.close()


# -- committed record + report contracts ------------------------------

def test_committed_serving_record_gates_hold():
    """The committed load-harness record
    (artifacts/ledger_serving_r14.jsonl) re-asserted: provenance
    present, batched throughput >= 3x the solo path at the equal
    request mix, per-request results bitwise equal to the solo runs,
    and steady-state p50 never hitting a compile (zero backend
    compiles in the measured window — cache verdict all-warm)."""
    events = telemetry.load_ledger(SERVING_RECORD, run="last")
    prov = events[0]
    assert prov["ev"] == "provenance"
    assert len(prov["git_commit"]) == 40
    gate = [e for e in events if e.get("ev") == "serving_gate"][-1]
    assert gate["ok"] is True
    assert gate["throughput_ratio"] >= 3.0
    assert gate["min_ratio"] >= 3.0
    assert gate["bitwise_equal"] is True and gate["mismatches"] == 0
    assert gate["steady_all_warm"] is True
    assert gate["measure_compiles"] == 0
    assert gate["coalesced"] is True and gate["max_batch_size"] > 1
    assert gate["solo"]["errors"] == 0 == gate["batched"]["errors"]
    # both legs summarized with the latency quantiles
    legs = {e["leg"]: e for e in events if e.get("ev") == "load_leg"}
    assert set(legs) == {"solo", "batched"}
    for leg in legs.values():
        assert leg["p50_ms"] <= leg["p95_ms"] <= leg["p99_ms"]
        assert leg["rps"] > 0
    # per-tick batch events carry the full schema
    batches = [e for e in events if e.get("ev") == "batch"]
    assert batches
    for e in batches:
        for k in ("queue_depth", "batch_size", "wait_ms_p50",
                  "run_ms", "compiles", "cache", "n_bucket"):
            assert k in e, (k, e)


MESHSERVE_RECORD = os.path.join(_REPO, "artifacts",
                                "ledger_meshserve_r21.jsonl")


def test_committed_meshserve_record_gates_hold():
    """The committed mesh-sharded serving capture
    (artifacts/ledger_meshserve_r21.jsonl) re-asserted: provenance
    present, gate green, per-request bitwise parity at thousands of
    connections, steady-all-warm (zero backend compiles inside every
    in-process measured window), and the scaling verdict HONEST — a
    record may only claim device scaling (``scaling_resolved``) when
    its host had at least peak-devices schedulable cores; otherwise it
    must say so and still clear the mesh-no-regression floor.  Either
    way the devices axis is pinned to never regress the solo path
    beyond the capture's own floor."""
    events = telemetry.load_ledger(MESHSERVE_RECORD, run="last")
    prov = events[0]
    assert prov["ev"] == "provenance"
    assert len(prov["git_commit"]) == 40
    gate = [e for e in events if e.get("ev") == "meshserve_gate"][-1]
    assert gate["ok"] is True
    assert gate["bitwise_equal"] is True and gate["mismatches"] == 0
    assert gate["steady_all_warm"] is True
    assert gate["measure_compiles"] == 0
    assert gate["errors"] == 0
    assert gate["connections"] >= 1024          # thousands, not a toy
    assert gate["peak_devices"] >= 4 > gate["base_devices"] == 1
    # the scaling verdict must be honest about the host
    if gate["scaling_resolved"]:
        assert gate["sched_cpus"] >= gate["peak_devices"]
        assert gate["min_ratio"] >= 1.5
        assert gate["devices_ratio"] >= gate["min_ratio"]
    else:
        assert gate["sched_cpus"] < gate["peak_devices"]
        assert gate["serial_host_floor"] is not None
        assert gate["devices_ratio"] >= gate["serial_host_floor"]
    # every leg summarized with the latency quantiles + its mesh width
    legs = {e["leg"]: e for e in events if e.get("ev") == "load_leg"}
    assert {f"mesh_r1_d{gate['base_devices']}",
            f"mesh_r1_d{gate['peak_devices']}"} <= set(legs)
    for leg in legs.values():
        assert leg["p50_ms"] <= leg["p95_ms"] <= leg["p99_ms"]
        assert leg["rps"] > 0 and leg["errors"] == 0
    # the peak leg's megabatches actually ran at the peak mesh width,
    # warm, at real batch sizes (the devices axis is not decorative)
    peak = [e for e in events if e.get("ev") == "batch"
            and e.get("devices") == gate["peak_devices"]]
    assert peak
    assert all(e["cache"] == "warm" for e in peak)
    assert max(e["batch_size"] for e in peak) >= 64


def test_batching_report_renders_committed_record():
    """tools/batching_report.render_serving_section (the ONE renderer
    telemetry_report embeds) against the committed record: histograms,
    leg table, and the gate verdict all render from artifact data
    alone."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "batching_report",
        os.path.join(_REPO, "tools", "batching_report.py"))
    br = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(br)
    events = telemetry.load_ledger(SERVING_RECORD, run="last")
    lines = br.render_serving_section(events)
    doc = "\n".join(lines)
    assert "## Serving batches" in doc
    assert "batch size histogram" in doc
    assert "Load-harness legs" in doc
    assert "| solo |" in doc and "| batched |" in doc
    assert "Serving gate: **green**" in doc
    # a non-serving ledger renders NO section (the report omits it)
    assert br.render_serving_section(
        [{"ev": "family", "family": "x"}]) == []
    # and the full telemetry report embeds the section
    rspec = importlib.util.spec_from_file_location(
        "telemetry_report",
        os.path.join(_REPO, "tools", "telemetry_report.py"))
    tr = importlib.util.module_from_spec(rspec)
    rspec.loader.exec_module(tr)
    md = tr.render_markdown(events)
    assert "## Serving batches" in md


# depth tier (tier-1 wall budget): the full load-harness smoke spins
# two live sidecars + warmup compiles (~1 min); the in-gate serving
# surface keeps test_sidecar_coalesces_concurrent_requests_bitwise
# (a real live batch through RPC) and the committed-record pins above
@pytest.mark.slow
def test_load_harness_smoke_live():
    """tools/load_harness --smoke end to end: tiny request mix, both
    legs live, equality + all-warm gates enforced (no throughput gate
    — host-noise-free ratios are the committed record's job)."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "load_harness", os.path.join(_REPO, "tools",
                                     "load_harness.py"))
    lh = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lh)
    assert lh.main(["--smoke", "--repeats", "1", "--workers", "2"]) \
        == 0
