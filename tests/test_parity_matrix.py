"""The parity-matrix artifact regenerates (VERDICT r3 item 4).

One race-free cell of artifacts/parity_r05.json is rebuilt end-to-end
through the same tool path that wrote the artifact (tools/parity_matrix
-> `gossip-tpu run --parity-check` subprocess -> both engines) and must
reproduce the exact-zero contract: on a power-of-two ring, jax rounds
and event-sim hop depths agree point for point in float32.
"""

import importlib.util
import os

# load-by-path, same pattern as test_bench_contract.py: tools/ must not
# join sys.path for the whole pytest session
_spec = importlib.util.spec_from_file_location(
    "parity_matrix",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 "tools", "parity_matrix.py"))
parity_matrix = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(parity_matrix)


def test_ring_1024_row_regenerates_exact():
    name, argv, timeout, tier = next(
        c for c in parity_matrix.CELLS if c[0] == "ring-1024")
    assert tier == parity_matrix.EXACT
    rep = parity_matrix.run_cell(name, argv, timeout)
    assert rep["curve_gap"] == 0.0
    assert rep["hop_bound_violation"] == 0.0
    assert rep["fixed_point_gap"] == 0.0
    assert rep["n"] == 1024 and rep["family"] == "ring"
    # both engines hit the default 0.99 target on the same round: the
    # k=2 ring floods 2 nodes/round from 1, so 1 + 2r >= ceil(0.99*1024)
    assert rep["jax"]["coverage"] == 1.0
    assert rep["jax"]["rounds"] == rep["gonative"]["rounds"] == 507
