"""Sharded round == single-device round, bitwise, on an 8-device CPU mesh.

This is the multi-device story the reference tested with N OS processes under
Maelstrom on one machine (SURVEY.md §4); we assert the much stronger property
that mesh sharding never changes the trajectory at all — every random draw is
keyed by global node id (ops/sampling), so coverage curves are bitwise equal.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gossip_tpu import config as C
from gossip_tpu.config import FaultConfig, ProtocolConfig, RunConfig
from gossip_tpu.models.si import coverage, make_si_round
from gossip_tpu.models.state import init_state
from gossip_tpu.parallel.sharded import (
    init_sharded_state, make_mesh, make_sharded_si_round, pad_to_mesh,
    simulate_until_sharded)
from gossip_tpu.topology import generators as G


def run_single(proto, topo, run, fault, rounds):
    step = jax.jit(make_si_round(proto, topo, fault, run.origin))
    st = init_state(run, proto, topo.n)
    for _ in range(rounds):
        st = step(st)
    return st


def run_sharded(proto, topo, run, fault, rounds, mesh):
    step = jax.jit(make_sharded_si_round(proto, topo, mesh, fault, run.origin))
    st = init_sharded_state(run, proto, topo, mesh)
    for _ in range(rounds):
        st = step(st)
    return st


CASES = [
    ("push-complete", ProtocolConfig(mode=C.PUSH, fanout=2, rumors=3),
     lambda: G.complete(96), None),
    ("pull-complete", ProtocolConfig(mode=C.PULL, fanout=1, rumors=2),
     lambda: G.complete(64), None),
    ("pushpull-er", ProtocolConfig(mode=C.PUSH_PULL, fanout=2),
     lambda: G.erdos_renyi(120, 0.08, seed=3), None),
    ("flood-ring", ProtocolConfig(mode=C.FLOOD),
     lambda: G.ring(96, 4), None),
    ("antientropy-ws", ProtocolConfig(mode=C.ANTI_ENTROPY, fanout=1, period=2),
     lambda: G.watts_strogatz(96, 4, 0.2, seed=1), None),
    ("push-drop-death", ProtocolConfig(mode=C.PUSH_PULL, fanout=2),
     lambda: G.erdos_renyi(96, 0.1, seed=5),
     FaultConfig(node_death_rate=0.1, drop_prob=0.2, seed=7)),
    ("flood-drop", ProtocolConfig(mode=C.FLOOD),
     lambda: G.ring(96, 4),
     FaultConfig(drop_prob=0.3, seed=2)),
    ("antientropy-fault", ProtocolConfig(mode=C.ANTI_ENTROPY, fanout=1,
                                         period=2),
     lambda: G.watts_strogatz(96, 4, 0.2, seed=1),
     FaultConfig(node_death_rate=0.15, drop_prob=0.1, seed=4)),
]


@pytest.mark.parametrize("name,proto,topo_fn,fault",
                         [pytest.param(*c, marks=pytest.mark.slow)
                          # slow tier (tier-1 wall budget): the combined
                          # fault case — both fault knobs stay smoked by
                          # flood-drop + antientropy-fault in the gate —
                          # and (txn-PR rebalance, ~8 s) the pushpull-ER
                          # param: pushpull stays smoked by
                          # push-complete + pull-complete, explicit
                          # tables by flood-ring/antientropy-ws
                          if c[0] in ("push-drop-death", "pushpull-er")
                          else c
                          for c in CASES],
                         ids=[c[0] for c in CASES])
def test_sharded_bitwise_equals_single(name, proto, topo_fn, fault):
    topo = topo_fn()
    run = RunConfig(seed=11)
    mesh = make_mesh(8)
    rounds = 6
    single = run_single(proto, topo, run, fault, rounds)
    sharded = run_sharded(proto, topo, run, fault, rounds, mesh)
    n = topo.n
    np.testing.assert_array_equal(
        np.asarray(sharded.seen)[:n], np.asarray(single.seen))
    assert float(sharded.msgs) == pytest.approx(float(single.msgs))


def test_padding_rows_stay_dark():
    # n=100 on 8 devices -> n_pad=104; rows 100..103 must never light up.
    topo = G.complete(100)
    proto = ProtocolConfig(mode=C.PUSH_PULL, fanout=3)
    mesh = make_mesh(8)
    st = run_sharded(proto, topo, RunConfig(seed=0), None, 8, mesh)
    assert pad_to_mesh(100, mesh, "nodes") == 104
    seen = np.asarray(st.seen)
    assert seen.shape[0] == 104
    assert not seen[100:].any()
    assert seen[:100].all()  # push-pull fanout 3, 8 rounds: converged


def test_simulate_until_sharded_converges():
    topo = G.erdos_renyi(500, 0.02, seed=2)
    proto = ProtocolConfig(mode=C.PUSH_PULL, fanout=2)
    mesh = make_mesh(8)
    rounds, cov, msgs, final = simulate_until_sharded(
        proto, topo, RunConfig(target_coverage=0.99, max_rounds=64), mesh)
    assert cov >= 0.99
    assert 0 < rounds < 64
    assert msgs > 0


# ~8 s (txn-PR rebalance): mesh-shape invariance stays pinned
# in-gate by every 1-vs-8 parity param above and the payload
# subsystems' 1-vs-4 parities (crdt/log/txn); the 1-vs-2-vs-4 sweep
# depth re-proves under -m slow
@pytest.mark.slow
def test_mesh_size_invariance():
    # 2-device and 8-device meshes give the same trajectory.
    topo = G.erdos_renyi(96, 0.1, seed=9)
    proto = ProtocolConfig(mode=C.PUSH_PULL, fanout=1)
    run = RunConfig(seed=3)
    a = run_sharded(proto, topo, run, None, 5, make_mesh(2))
    b = run_sharded(proto, topo, run, None, 5, make_mesh(8))
    np.testing.assert_array_equal(
        np.asarray(a.seen)[:topo.n], np.asarray(b.seen)[:topo.n])
