"""Checkpoint/resume for the sharded and fused engines (VERDICT r3 #3).

The flagship sharded/fused runs are the only runs long enough to need
persistence — the reference loses everything on process death
(main.go:22-26; SURVEY.md §5 "Checkpoint/resume: None").  Contract under
test, per engine: an interrupted run (save at round k, new process, load,
continue) is BITWISE equal to an uninterrupted run of the same budget —
state arrays, message accounting, round counter, and (new in round 4)
the per-round coverage curve captured while checkpointing.

The fused-plane tests run the CPU interpreter (stubbed-but-deterministic
hardware PRNG): degenerate epidemics, exact resume semantics.
"""

import json
import os
import subprocess
import sys

import jax
import numpy as np
import pytest

from gossip_tpu.config import ProtocolConfig, RunConfig
from gossip_tpu.models.si_packed import init_packed_state, make_packed_round
from gossip_tpu.ops.pallas_round import FusedState
from gossip_tpu.parallel.sharded import make_mesh
from gossip_tpu.parallel.sharded_fused import (
    checkpointed_fused_planes, make_plane_mesh, plane_count)
from gossip_tpu.parallel.sharded_packed import checkpointed_packed_sharded
from gossip_tpu.topology import generators as G
from gossip_tpu.utils.checkpoint import load_meta, load_state
from gossip_tpu.utils.metrics import load_curve_jsonl

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 4, reason="needs the virtual multi-device mesh")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# Children INHERIT the session-scoped compile cache conftest put in
# GOSSIP_COMPILE_CACHE (a fresh temp dir — never the developer's
# ~/.cache, which the old "" pin guarded against): every CLI re-exec
# below runs the SAME 200-node shapes, so the first child compiles and
# the rest start warm — what moved the resume tests below back out of
# `slow` into tier-1 (compile-once PR).
CLI_ENV = {**os.environ, "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
           "PYTHONPATH": _REPO}


def _cli(*argv):
    return subprocess.run([sys.executable, "-m", "gossip_tpu", *argv],
                          capture_output=True, text=True, cwd=_REPO,
                          env=CLI_ENV, timeout=240)


def _packed_run(tmp_path, name, max_rounds, resume_state=None,
                want_curve=False, curve_prefix=(), every=3):
    proto = ProtocolConfig(mode="pull", fanout=1, rumors=3)
    topo = G.erdos_renyi(200, 0.06, seed=4)
    run = RunConfig(seed=11, max_rounds=max_rounds)
    mesh = make_mesh(4)
    return checkpointed_packed_sharded(
        proto, topo, run, mesh, str(tmp_path / name), every=every,
        resume_state=resume_state, want_curve=want_curve,
        curve_prefix=curve_prefix)


@pytest.mark.slow
def test_sharded_packed_resume_bitwise(tmp_path):
    # uninterrupted 8-round run vs 4 rounds + load-in-"new-process" + 4
    full, cov_full, _ = _packed_run(tmp_path, "full.npz", 8)
    half, _, _ = _packed_run(tmp_path, "half.npz", 4)
    loaded = load_state(str(tmp_path / "half.npz"))
    assert int(loaded.round) == 4
    resumed, cov_res, _ = _packed_run(tmp_path, "half.npz", 8,
                                      resume_state=loaded)
    np.testing.assert_array_equal(np.asarray(full.seen),
                                  np.asarray(resumed.seen))
    assert int(full.round) == int(resumed.round) == 8
    assert float(full.msgs) == float(resumed.msgs)
    assert cov_full == cov_res


@pytest.mark.slow
def test_sharded_packed_checkpoint_curve_resumes(tmp_path):
    # the curve persists in the checkpoint and the resumed curve equals
    # the uninterrupted one point-for-point
    _, _, curve_full = _packed_run(tmp_path, "cfull.npz", 8,
                                   want_curve=True)
    assert len(curve_full) == 8
    _, _, curve_half = _packed_run(tmp_path, "chalf.npz", 5,
                                   want_curve=True)
    meta = load_meta(str(tmp_path / "chalf.npz"))
    saved_curve = meta["extra"]["curve"]
    assert saved_curve == curve_half and len(saved_curve) == 5
    loaded = load_state(str(tmp_path / "chalf.npz"))
    _, _, curve_res = _packed_run(tmp_path, "chalf.npz", 8,
                                  resume_state=loaded, want_curve=True,
                                  curve_prefix=saved_curve)
    assert curve_res == curve_full
    # monotone epidemic sanity on the real prefix
    assert all(b >= a - 1e-6 for a, b in zip(curve_res, curve_res[1:]))


@pytest.mark.slow
def test_sharded_packed_checkpoint_matches_plain_driver(tmp_path):
    # the segmented checkpointed trajectory equals the single-device
    # packed reference on the unpadded prefix (same seeds, same kernels)
    proto = ProtocolConfig(mode="pull", fanout=1, rumors=2)
    topo = G.erdos_renyi(160, 0.08, seed=6)
    run = RunConfig(seed=5, max_rounds=6)
    final, _, _ = checkpointed_packed_sharded(
        proto, topo, run, make_mesh(4), str(tmp_path / "ck.npz"), every=2)
    step = jax.jit(make_packed_round(proto, topo))
    ref = init_packed_state(run, proto, topo.n)
    for _ in range(6):
        ref = step(ref)
    np.testing.assert_array_equal(np.asarray(final.seen)[:160],
                                  np.asarray(ref.seen)[:160])


def _fused_run(tmp_path, name, max_rounds, resume_state=None,
               want_curve=False, curve_prefix=(), every=2):
    n, rumors = 128 * 8, 40
    run = RunConfig(seed=3, max_rounds=max_rounds)
    mesh = make_plane_mesh(4)
    return checkpointed_fused_planes(
        n, rumors, run, mesh, str(tmp_path / name), every=every,
        resume_state=resume_state, want_curve=want_curve,
        curve_prefix=curve_prefix, interpret=True)


def test_fused_planes_resume_bitwise(tmp_path):
    full, cov_full, _ = _fused_run(tmp_path, "full.npz", 6)
    assert full.table.shape[0] == plane_count(40, 4)
    _fused_run(tmp_path, "half.npz", 3)
    loaded = load_state(str(tmp_path / "half.npz"))
    assert isinstance(loaded, FusedState) and int(loaded.round) == 3
    resumed, cov_res, _ = _fused_run(tmp_path, "half.npz", 6,
                                     resume_state=loaded)
    np.testing.assert_array_equal(np.asarray(full.table),
                                  np.asarray(resumed.table))
    assert int(resumed.round) == 6
    assert float(full.msgs) == float(resumed.msgs)
    assert cov_full == cov_res


def test_fused_planes_checkpoint_curve(tmp_path):
    _, _, curve_full = _fused_run(tmp_path, "cfull.npz", 5,
                                  want_curve=True)
    assert len(curve_full) == 5
    _, _, _ = _fused_run(tmp_path, "chalf.npz", 2, want_curve=True)
    saved = load_meta(str(tmp_path / "chalf.npz"))["extra"]["curve"]
    assert len(saved) == 2
    loaded = load_state(str(tmp_path / "chalf.npz"))
    _, _, curve_res = _fused_run(tmp_path, "chalf.npz", 5,
                                 resume_state=loaded, want_curve=True,
                                 curve_prefix=saved)
    assert curve_res == curve_full


# depth tier (tier-1 wall budget, CRDT-PR rebalance): 3 CLI children
# (~32 s warm).  The surface keeps in-gate coverage twice over: the
# CLI checkpoint+curve path via test_cli_save_curve_with_checkpoint
# below, and the sharded-packed resume bitwise contract via
# tests/test_crash_safety.py::test_packed_sharded_resume_under_fault_
# bitwise (which additionally runs it under a fault program).
@pytest.mark.slow
def test_cli_sharded_checkpoint_resume_and_curve(tmp_path):
    ck = str(tmp_path / "cli.npz")
    args = ("run", "--mode", "pull", "--family", "erdos_renyi",
            "--n", "200", "--p", "0.06", "--devices", "4",
            "--seed", "11", "--checkpoint", ck, "--checkpoint-every", "3", "--curve")
    p = _cli(*args, "--max-rounds", "4")
    assert p.returncode == 0, p.stderr
    first = json.loads(p.stdout)
    assert first["engine"] == "sharded-packed" and first["rounds"] == 4
    assert len(first["curve"]) == 4
    p = _cli(*args, "--max-rounds", "8", "--resume")
    assert p.returncode == 0, p.stderr
    rep = json.loads(p.stdout)
    assert rep["resumed"] and rep["rounds"] == 8
    assert rep["curve"][:4] == first["curve"]
    # uninterrupted reference run through the same CLI path
    p = _cli(*("run", "--mode", "pull", "--family", "erdos_renyi",
               "--n", "200", "--p", "0.06", "--devices", "4",
               "--seed", "11", "--checkpoint", str(tmp_path / "ref.npz"),
               "--checkpoint-every", "3", "--curve", "--max-rounds", "8"))
    assert p.returncode == 0, p.stderr
    ref = json.loads(p.stdout)
    assert rep["curve"] == ref["curve"]
    assert rep["coverage"] == ref["coverage"]
    assert rep["msgs"] == ref["msgs"]


@pytest.mark.slow       # 6 CLI children: the ~3 s/child interpreter+
def test_cli_checkpoint_error_paths(tmp_path):   # jax-import floor
    # dominates even fully warm — stays out of the tier-1 gate
    ck = str(tmp_path / "e.npz")
    # fused engine off-TPU: the shared ineligibility list speaks
    p = _cli("run", "--mode", "pull", "--n", "1024", "--engine", "fused",
             "--checkpoint", ck)
    assert p.returncode == 2
    assert "needs a TPU" in p.stderr
    # curve-history mismatch, both directions
    base = ("run", "--mode", "pull", "--family", "erdos_renyi",
            "--n", "200", "--p", "0.06", "--devices", "4",
            "--seed", "11", "--checkpoint", ck)
    p = _cli(*base, "--max-rounds", "3")
    assert p.returncode == 0, p.stderr
    p = _cli(*base, "--max-rounds", "6", "--resume", "--curve")
    assert p.returncode == 2 and "no curve history" in p.stderr
    p = _cli(*base, "--max-rounds", "3", "--curve")   # fresh, with curve
    assert p.returncode == 0, p.stderr
    p = _cli(*base, "--max-rounds", "6", "--resume")
    assert p.returncode == 2 and "carries a curve" in p.stderr
    # config-fingerprint mismatch still refuses (devices now included)
    p = _cli(*("run", "--mode", "pull", "--family", "erdos_renyi",
               "--n", "200", "--p", "0.06", "--devices", "2",
               "--seed", "11", "--checkpoint", ck,
               "--max-rounds", "6", "--resume", "--curve"))
    assert p.returncode == 2 and "config mismatch" in p.stderr


# depth tier (tier-1 wall budget, PR 7 rebalance): the single-device
# CLI checkpoint path is exercised in-gate end-to-end by the crashloop
# smoke (kill + resume + curve-less report contract) and the sharded
# CLI resume test below; the curve-composition depth runs under -m slow
@pytest.mark.slow
def test_cli_single_device_checkpoint_curve(tmp_path):
    # the round-4 curve capture also lands on the original single-device
    # SI driver (engine label si-xla), resume included
    ck = str(tmp_path / "one.npz")
    base = ("run", "--mode", "pushpull", "--family", "erdos_renyi",
            "--n", "150", "--p", "0.08", "--seed", "7",
            "--checkpoint", ck, "--checkpoint-every", "2", "--curve")
    p = _cli(*base, "--max-rounds", "3")
    assert p.returncode == 0, p.stderr
    first = json.loads(p.stdout)
    assert first["engine"] == "si-xla" and len(first["curve"]) == 3
    p = _cli(*base, "--max-rounds", "6", "--resume")
    assert p.returncode == 0, p.stderr
    rep = json.loads(p.stdout)
    assert rep["curve"][:3] == first["curve"] and len(rep["curve"]) == 6


# slow tier (tier-1 wall budget): legacy-fingerprint depth; resume
# stays gated via test_cli_sharded_checkpoint_resume_and_curve
@pytest.mark.slow
def test_cli_resume_accepts_pre_round4_fingerprint(tmp_path):
    # checkpoints written before the devices/exchange/engine keys existed
    # (all single-device XLA) must still resume: missing keys default
    ck = str(tmp_path / "old.npz")
    base = ("run", "--mode", "pushpull", "--n", "150",
            "--family", "erdos_renyi", "--p", "0.08", "--seed", "7",
            "--checkpoint", ck)
    p = _cli(*base, "--max-rounds", "3")
    assert p.returncode == 0, p.stderr
    with np.load(ck, allow_pickle=False) as z:
        arrays = {k: z[k] for k in z.files if k != "__meta__"}
        meta = json.loads(str(z["__meta__"]))
    for k in ("devices", "exchange", "engine"):
        del meta["extra"]["config"][k]
    np.savez(ck, __meta__=json.dumps(meta), **arrays)
    p = _cli(*base, "--max-rounds", "5", "--resume")
    assert p.returncode == 0, p.stderr
    assert json.loads(p.stdout)["rounds"] == 5


def test_cli_save_curve_with_checkpoint(tmp_path):
    ck = str(tmp_path / "s.npz")
    curve_path = str(tmp_path / "curve.jsonl")
    p = _cli("run", "--mode", "pull", "--family", "erdos_renyi",
             "--n", "200", "--p", "0.06", "--devices", "4",
             "--seed", "11", "--checkpoint", ck,
             "--max-rounds", "4", "--save-curve", curve_path)
    assert p.returncode == 0, p.stderr
    rows = load_curve_jsonl(curve_path)
    assert rows[0]["meta"]["engine"] == "sharded-packed"
    points = [r for r in rows if "coverage" in r]
    assert len(points) == 4 and points[-1]["round"] == 4


# ---------------------------------------------------------------------------
# SWIM and rumor checkpointing (round 4: the two modes the --checkpoint
# driver used to refuse; engines runtime/simulator.checkpointed_swim and
# models/rumor.checkpointed_rumor)

def _swim_cfg():
    proto = ProtocolConfig(mode="swim", fanout=2, swim_proxies=2,
                           swim_subjects=4, swim_suspect_rounds=4)
    run = RunConfig(seed=9, max_rounds=12)
    return proto, run, (1,), 2        # dead subjects, fail_round


# slow tier (tier-1 wall budget): the rumor twin keeps streaming-
# vs-checkpointed resume gated
@pytest.mark.slow
def test_checkpointed_swim_matches_streaming_and_resumes(tmp_path):
    from gossip_tpu.runtime.simulator import (checkpointed_swim,
                                              simulate_swim_curve)
    proto, run, dead, fr = _swim_cfg()
    n = 96
    # streaming reference (one lax.scan, no checkpointing)
    fracs, ref = simulate_swim_curve(proto, n, run.max_rounds,
                                     dead_nodes=dead, fail_round=fr,
                                     seed=run.seed)
    full, det_full, curve_full = checkpointed_swim(
        proto, n, run, str(tmp_path / "sfull.npz"), every=5,
        dead_nodes=dead, fail_round=fr, want_curve=True)
    np.testing.assert_array_equal(np.asarray(full.wire),
                                  np.asarray(ref.wire))
    np.testing.assert_array_equal(np.asarray(full.timer),
                                  np.asarray(ref.timer))
    np.testing.assert_allclose(curve_full, np.asarray(fracs), rtol=0,
                               atol=0)
    assert det_full == float(fracs[-1])
    # interrupted at 7, resumed to 12 in a "new process" (fresh load)
    half_run = RunConfig(seed=9, max_rounds=7)
    checkpointed_swim(proto, n, half_run, str(tmp_path / "shalf.npz"),
                      every=5, dead_nodes=dead, fail_round=fr,
                      want_curve=True)
    meta = load_meta(str(tmp_path / "shalf.npz"))
    loaded = load_state(str(tmp_path / "shalf.npz"))
    assert int(loaded.round) == 7
    res, det_res, curve_res = checkpointed_swim(
        proto, n, run, str(tmp_path / "shalf.npz"), every=5,
        dead_nodes=dead, fail_round=fr, resume_state=loaded,
        want_curve=True, curve_prefix=meta["extra"]["curve"])
    np.testing.assert_array_equal(np.asarray(res.wire),
                                  np.asarray(full.wire))
    assert curve_res == curve_full
    assert float(res.msgs) == float(full.msgs)


@pytest.mark.slow
def test_checkpointed_swim_sharded_bitwise_matches_single(tmp_path):
    from gossip_tpu.runtime.simulator import checkpointed_swim
    proto, run, dead, fr = _swim_cfg()
    n, mesh = 96, make_mesh(8)
    single, det_s, curve_s = checkpointed_swim(
        proto, n, run, str(tmp_path / "s1.npz"), every=5,
        dead_nodes=dead, fail_round=fr, want_curve=True)
    full, det_m, curve_m = checkpointed_swim(
        proto, n, run, str(tmp_path / "s8.npz"), every=5,
        dead_nodes=dead, fail_round=fr, mesh=mesh, want_curve=True)
    np.testing.assert_array_equal(np.asarray(full.wire)[:n],
                                  np.asarray(single.wire))
    assert curve_m == curve_s and det_m == det_s
    # resume the sharded run (host-loaded rows re-placed on the mesh)
    half_run = RunConfig(seed=9, max_rounds=7)
    checkpointed_swim(proto, n, half_run, str(tmp_path / "s8h.npz"),
                      every=5, dead_nodes=dead, fail_round=fr, mesh=mesh,
                      want_curve=True)
    meta = load_meta(str(tmp_path / "s8h.npz"))
    loaded = load_state(str(tmp_path / "s8h.npz"))
    res, _, curve_res = checkpointed_swim(
        proto, n, run, str(tmp_path / "s8h.npz"), every=5,
        dead_nodes=dead, fail_round=fr, mesh=mesh, resume_state=loaded,
        want_curve=True, curve_prefix=meta["extra"]["curve"])
    np.testing.assert_array_equal(np.asarray(res.wire),
                                  np.asarray(full.wire))
    assert curve_res == curve_m


# depth tier (tier-1 wall budget, PR 7 rebalance): the checkpointed
# rumor surface keeps in-gate pins via the crash-safety resume-under-
# fault test and the ckpt-static fingerprint; the streaming-parity
# cross-check runs under -m slow
@pytest.mark.slow
def test_checkpointed_rumor_matches_streaming_and_resumes(tmp_path):
    from gossip_tpu.models.rumor import (checkpointed_rumor,
                                         simulate_curve_rumor)
    proto = ProtocolConfig(mode="rumor", fanout=1, rumors=3, rumor_k=2)
    topo = G.erdos_renyi(200, 0.04, seed=7)
    run = RunConfig(seed=13, max_rounds=18)
    covs, hots, _, ref = simulate_curve_rumor(proto, topo, run)
    full, cov_full, residue, curve = checkpointed_rumor(
        proto, topo, run, str(tmp_path / "rfull.npz"), every=7,
        want_curve=True)
    np.testing.assert_array_equal(np.asarray(full.seen),
                                  np.asarray(ref.seen))
    np.testing.assert_array_equal(np.asarray(full.hot),
                                  np.asarray(ref.hot))
    np.testing.assert_array_equal(np.asarray(full.cnt),
                                  np.asarray(ref.cnt))
    np.testing.assert_allclose(curve["coverage"], np.asarray(covs),
                               rtol=0, atol=0)
    np.testing.assert_allclose(curve["hot"], np.asarray(hots), rtol=0,
                               atol=0)
    assert residue == 1.0 - cov_full
    # resume: named channels round-trip through the checkpoint metadata
    half = RunConfig(seed=13, max_rounds=9)
    checkpointed_rumor(proto, topo, half, str(tmp_path / "rhalf.npz"),
                       every=7, want_curve=True)
    meta = load_meta(str(tmp_path / "rhalf.npz"))
    saved = meta["extra"]["curve"]
    assert set(saved) == {"coverage", "hot"} and len(saved["hot"]) == 9
    loaded = load_state(str(tmp_path / "rhalf.npz"))
    res, cov_res, _, curve_res = checkpointed_rumor(
        proto, topo, run, str(tmp_path / "rhalf.npz"), every=7,
        resume_state=loaded, want_curve=True, curve_prefix=saved)
    np.testing.assert_array_equal(np.asarray(res.seen),
                                  np.asarray(full.seen))
    assert curve_res == curve and cov_res == cov_full


@pytest.mark.slow
def test_checkpointed_rumor_sharded_matches_single(tmp_path):
    from gossip_tpu.models.rumor import checkpointed_rumor
    proto = ProtocolConfig(mode="rumor", fanout=1, rumors=2, rumor_k=2)
    topo = G.erdos_renyi(160, 0.05, seed=8)
    run = RunConfig(seed=4, max_rounds=14)
    _, cov_s, _, curve_s = checkpointed_rumor(
        proto, topo, run, str(tmp_path / "r1.npz"), every=5,
        want_curve=True)
    final, cov_m, _, curve_m = checkpointed_rumor(
        proto, topo, run, str(tmp_path / "r8.npz"), every=5,
        mesh=make_mesh(8), want_curve=True)
    # metric curves/final differ in reduction ORDER (weighted sum over
    # the padded rows vs plain mean), so the last float32 bit may
    # differ even though the state trajectory is bitwise equal
    assert set(curve_m) == set(curve_s)
    for ch in curve_s:
        np.testing.assert_allclose(curve_m[ch], curve_s[ch], rtol=0,
                                   atol=1e-6)
    assert cov_m == pytest.approx(cov_s, abs=1e-6)
    assert final.seen.shape[0] >= 160     # padded rows in the checkpoint


@pytest.mark.slow
def test_cli_swim_checkpoint_resume(tmp_path):
    ck = str(tmp_path / "sw.npz")
    args = ("run", "--n", "300", "--mode", "swim", "--fanout", "2",
            "--swim-subjects", "4", "--swim-proxies", "2",
            "--swim-suspect-rounds", "4", "--checkpoint", ck,
            "--checkpoint-every", "5", "--curve")
    r1 = _cli(*args, "--max-rounds", "7")
    assert r1.returncode == 0, r1.stderr
    r2 = _cli(*args, "--max-rounds", "12", "--resume")
    assert r2.returncode == 0, r2.stderr
    out = json.loads(r2.stdout.strip().splitlines()[-1])
    assert out["resumed"] and out["rounds"] == 12
    assert out["engine"] == "swim-xla"
    assert out["metric"] == "detection_fraction"
    # uninterrupted reference run, same flags
    ref = _cli("run", "--n", "300", "--mode", "swim", "--fanout", "2",
               "--swim-subjects", "4", "--swim-proxies", "2",
               "--swim-suspect-rounds", "4", "--checkpoint",
               str(tmp_path / "ref.npz"), "--checkpoint-every", "5",
               "--curve", "--max-rounds", "12")
    ref_out = json.loads(ref.stdout.strip().splitlines()[-1])
    assert out["curve"] == ref_out["curve"]
    assert out["msgs"] == ref_out["msgs"]


# slow tier (tier-1 wall budget): rumor CLI checkpointing stays
# gated via test_checkpointed_rumor_matches_streaming_and_resumes
@pytest.mark.slow
def test_cli_rumor_checkpoint_carries_extinction(tmp_path):
    ck = str(tmp_path / "ru.npz")
    args = ("run", "--n", "400", "--mode", "rumor", "--family",
            "erdos_renyi", "--p", "0.02", "--fanout", "1", "--rumors",
            "3", "--checkpoint", ck, "--checkpoint-every", "7",
            "--curve")
    r1 = _cli(*args, "--max-rounds", "9")
    assert r1.returncode == 0, r1.stderr
    r2 = _cli(*args, "--max-rounds", "30", "--resume")
    assert r2.returncode == 0, r2.stderr
    out = json.loads(r2.stdout.strip().splitlines()[-1])
    assert out["engine"] == "rumor-xla" and out["resumed"]
    assert len(out["curve"]) == 30 and len(out["hot_curve"]) == 30
    assert out["residue"] == pytest.approx(1.0 - out["coverage"])
    if out["extinct"]:
        er = out["extinction_round"]
        assert er > 0 and out["hot_curve"][er - 1] == 0.0
        assert all(h > 0.0 for h in out["hot_curve"][:er - 1])
