"""SIR rumor-mongering tests (models/rumor.py).

The exact 2-node scenarios are fully deterministic — with exclude_self
on a 2-node complete graph there is only one possible partner — so they
pin the counter semantics (feedback vs blind) without touching RNG.
"""

import numpy as np
import pytest

from gossip_tpu.backend import run_simulation
from gossip_tpu.config import (FaultConfig, MeshConfig, ProtocolConfig,
                               RunConfig, TopologyConfig)
from gossip_tpu.models.rumor import (init_rumor_state, make_rumor_round,
                                     simulate_curve_rumor,
                                     simulate_until_rumor)
from gossip_tpu.topology import generators as G


def _run(n=2048, variant="feedback", k=2, fanout=1, max_rounds=256,
         fault=None, family="complete", seed=0):
    proto = ProtocolConfig(mode="rumor", fanout=fanout, rumor_k=k,
                           rumor_variant=variant)
    topo = (G.complete(n) if family == "complete"
            else G.build(TopologyConfig(family=family, n=n, k=6, p=0.1)))
    run = RunConfig(max_rounds=max_rounds, seed=seed)
    return simulate_until_rumor(proto, topo, run, fault)


def test_two_node_feedback_exact():
    # r1: 0 pushes to 1 (1 didn't know: no hit). r2: both push (both knew:
    # cnt=1 each). r3: both push again (cnt=2 -> removed). 5 msgs total.
    rounds, cov, residue, msgs, final = _run(n=2, variant="feedback", k=2)
    assert (rounds, msgs) == (3, 5.0)
    assert cov == 1.0 and residue == 0.0
    assert not bool(np.asarray(final.hot).any())


def test_two_node_blind_exact():
    # r1: 0 pushes (cnt0=1), 1 infected. r2: both push (cnt0=2 -> removed,
    # cnt1=1). r3: 1 pushes (cnt1=2 -> removed). 4 msgs total.
    rounds, cov, residue, msgs, final = _run(n=2, variant="blind", k=2)
    assert (rounds, msgs) == (3, 4.0)
    assert cov == 1.0


def test_terminates_with_low_residue_feedback():
    rounds, cov, residue, msgs, final = _run(n=2048, variant="feedback", k=3)
    assert not bool(np.asarray(final.hot).any())      # self-terminated
    assert rounds < 256
    assert cov > 0.9                                   # Demers ballpark
    assert residue == pytest.approx(1.0 - cov)


def test_blind_message_bound_and_more_residue():
    # Blind counter k: every (node, rumor) pushes at most
    # fanout * ceil(k / fanout) <= k + fanout - 1 times — a hard traffic
    # bound SI push has no analog of.
    n, k, fanout = 4096, 2, 2
    rounds, cov_b, residue_b, msgs, _ = _run(n=n, variant="blind", k=k,
                                             fanout=fanout)
    assert msgs <= n * (k + fanout - 1)
    # feedback at the same k informs at least as many nodes (it only
    # stops on evidence of redundancy, blind stops unconditionally)
    _, cov_f, _, _, _ = _run(n=n, variant="feedback", k=k, fanout=fanout)
    assert cov_f >= cov_b


def test_monotone_seen_and_curve_matches_until():
    proto = ProtocolConfig(mode="rumor", fanout=1, rumor_k=2)
    topo = G.complete(1024)
    run = RunConfig(max_rounds=128, seed=7)
    covs, hots, msgs, final = simulate_curve_rumor(proto, topo, run)
    covs = np.asarray(covs)
    assert (np.diff(covs) >= -1e-7).all()              # monotone coverage
    # the infective wave rises then dies out
    assert float(hots[-1]) == 0.0
    assert hots.max() > 0.1
    rounds, cov, _, msgs_u, _ = simulate_until_rumor(proto, topo, run)
    assert cov == pytest.approx(float(covs[-1]))
    assert msgs_u == pytest.approx(float(msgs[-1]))


# ~8 s (txn-PR rebalance): the static-death rumor surface stays
# smoked in-gate by the nemesis rumor-churn ensemble parity
# (tests/test_nemesis.py) and the rumor_sir dry-run family; this
# 256-round depth re-proves under -m slow
@pytest.mark.slow
def test_dead_nodes_stay_dark():
    fault = FaultConfig(node_death_rate=0.2, seed=3)
    proto = ProtocolConfig(mode="rumor", fanout=2, rumor_k=3)
    topo = G.complete(512)
    run = RunConfig(max_rounds=256, seed=1)
    rounds, cov, residue, msgs, final = simulate_until_rumor(
        proto, topo, run, fault)
    from gossip_tpu.models.state import alive_mask
    alive = np.asarray(alive_mask(fault, 512, 0))      # origin pinned alive,
    seen = np.asarray(final.seen)                      # like the kernel
    hot = np.asarray(final.hot)
    assert not seen[~alive].any()                      # dead never informed
    assert not hot[~alive].any()
    assert cov > 0.9                                   # alive population
    # the curve driver weights by the SAME mask: with every alive node
    # informed, coverage reads ~1.0 (dead nodes are unreachable, not
    # uninformed) and the backend's rounds are extinction rounds in both
    # driver shapes
    curve_rep = run_simulation("jax-tpu", proto,
                               TopologyConfig(family="complete", n=512),
                               run, fault=fault, want_curve=True)
    until_rep = run_simulation("jax-tpu", proto,
                               TopologyConfig(family="complete", n=512),
                               run, fault=fault)
    assert curve_rep.coverage == pytest.approx(cov, abs=1e-6)
    assert curve_rep.meta["rounds_semantics"] == "extinction"
    assert curve_rep.rounds == until_rep.rounds == rounds


def test_backend_routing_and_rejections():
    rep = run_simulation("jax-tpu",
                         ProtocolConfig(mode="rumor", rumor_k=2),
                         TopologyConfig(family="complete", n=1024),
                         RunConfig(max_rounds=128))
    assert rep.mode == "rumor"
    assert rep.meta["variant"] == "feedback"
    assert rep.meta["terminated"] is True
    assert rep.meta["residue"] == pytest.approx(1.0 - rep.coverage, abs=1e-6)
    assert rep.rounds > 0
    with pytest.raises(ValueError, match="pull rounds only"):
        run_simulation("jax-tpu", ProtocolConfig(mode="rumor"),
                       TopologyConfig(family="complete", n=1024),
                       RunConfig(engine="fused"))
    with pytest.raises(ValueError, match="rumor_k"):
        ProtocolConfig(mode="rumor", rumor_k=0)
    with pytest.raises(ValueError, match="rumor_variant"):
        ProtocolConfig(mode="rumor", rumor_variant="telepathy")
    # the SI builders refuse SIR mode loudly (no silent no-op rounds)
    from gossip_tpu.models.si import make_si_round
    from gossip_tpu.parallel.sharded import make_mesh, make_sharded_si_round
    with pytest.raises(ValueError, match="rumor"):
        make_si_round(ProtocolConfig(mode="rumor"), G.complete(64))
    with pytest.raises(ValueError, match="rumor"):
        make_sharded_si_round(ProtocolConfig(mode="rumor"), G.complete(64),
                              make_mesh(8))


def test_works_on_explicit_tables():
    rounds, cov, residue, msgs, _ = _run(n=2048, family="watts_strogatz",
                                         k=3, fanout=2)
    assert cov > 0.8


@pytest.mark.parametrize("variant", [
    pytest.param("feedback", marks=pytest.mark.slow),
    pytest.param("blind", marks=pytest.mark.slow)])
def test_sharded_rumor_bitwise_parity(variant):
    """The shard_map twin is bitwise-identical to the single-device
    kernel — same per-node threefry streams (keyed by global id), same
    counters — on the 8-device CPU mesh, padding included."""
    import jax

    from gossip_tpu.models.rumor import make_rumor_round
    from gossip_tpu.parallel.sharded import make_mesh
    from gossip_tpu.parallel.sharded_rumor import (
        init_sharded_rumor_state, make_sharded_rumor_round)

    n = 1000                       # NOT divisible by 8: padding exercised
    proto = ProtocolConfig(mode="rumor", fanout=2, rumor_k=2,
                           rumor_variant=variant, rumors=3)
    topo = G.complete(n)
    run = RunConfig(seed=11, max_rounds=32)
    mesh = make_mesh(8)

    step_1 = make_rumor_round(proto, topo)
    st1 = init_rumor_state(run, proto, n)
    step_8, tables = make_sharded_rumor_round(proto, topo, mesh, tabled=True)
    st8 = init_sharded_rumor_state(run, proto, topo, mesh)
    for _ in range(10):
        st1 = step_1(st1)
        st8 = step_8(st8, *tables)
    for field in ("seen", "hot", "cnt"):
        a = np.asarray(getattr(st1, field))
        b = np.asarray(getattr(st8, field))[:n]
        np.testing.assert_array_equal(a, b, err_msg=field)
    assert float(st1.msgs) == float(st8.msgs)


@pytest.mark.slow
def test_sharded_rumor_until_matches_single():
    from gossip_tpu.parallel.sharded import make_mesh
    from gossip_tpu.parallel.sharded_rumor import (
        simulate_until_rumor_sharded)

    proto = ProtocolConfig(mode="rumor", fanout=1, rumor_k=2)
    topo = G.complete(2048)
    run = RunConfig(seed=4, max_rounds=256)
    single = simulate_until_rumor(proto, topo, run)
    sharded = simulate_until_rumor_sharded(proto, topo, run, make_mesh(8))
    assert single[:4] == sharded[:4]       # rounds, cov, residue, msgs
    # ... and through the backend seam
    rep = run_simulation("jax-tpu", proto,
                         TopologyConfig(family="complete", n=2048),
                         run, mesh_cfg=MeshConfig(n_devices=8))
    assert rep.meta["devices"] == 8
    assert rep.meta["terminated"] is True
    assert rep.rounds == single[0]


# ~5 s (txn-PR rebalance): the rumor ensemble's churn twin
# (test_ensemble_rumor_churn_matches_solo, tests/test_nemesis.py)
# keeps the vmapped-SIR solo-parity surface in-gate; the fault-free
# depth re-proves under -m slow
@pytest.mark.slow
def test_rumor_seed_ensemble_matches_solo_trajectories():
    """One vmapped XLA program = |seeds| SIR trajectories, each bitwise
    equal to its solo scan; residue/extinction stats come out."""
    from gossip_tpu.parallel.sweep import ensemble_rumor_curves
    proto = ProtocolConfig(mode="rumor", fanout=1, rumor_k=2)
    topo = G.complete(1024)
    run = RunConfig(max_rounds=96, seed=3)
    seeds = [3, 4, 5, 6]
    ens = ensemble_rumor_curves(proto, topo, run, seeds)
    assert ens.curves.shape == (4, 96)
    s = ens.summary()
    assert s["terminated"] == 4
    assert 0.0 <= s["residue_p95"] <= 1.0
    assert s["extinction_rounds_mean"] > 0
    # row 1 (seed 4) must equal the solo curve driver with seed 4
    solo_covs, solo_hots, solo_msgs, _ = simulate_curve_rumor(
        proto, topo, RunConfig(max_rounds=96, seed=4))
    np.testing.assert_array_equal(ens.curves[1], np.asarray(solo_covs))
    np.testing.assert_array_equal(ens.hot[1], np.asarray(solo_hots))
    np.testing.assert_array_equal(ens.msgs[1], np.asarray(solo_msgs))
    # extinction round of row 1 agrees with the solo hot curve
    idx = np.nonzero(np.asarray(solo_hots) == 0.0)[0]
    assert ens.extinction_rounds[1] == idx[0] + 1


@pytest.mark.slow
def test_sharded_rumor_curve_matches_single():
    """Round-4: sharded rumor CURVE capture (the last rumor carve-out).
    Both channels — coverage and hot fraction — match the single-device
    scan point for point on a padded mesh, and the backend routes
    want_curve + devices>1 to it instead of refusing."""
    from gossip_tpu.models.rumor import simulate_curve_rumor
    from gossip_tpu.parallel.sharded import make_mesh
    from gossip_tpu.parallel.sharded_rumor import (
        simulate_curve_rumor_sharded)

    n = 300                        # not divisible by 8: padding exercised
    proto = ProtocolConfig(mode="rumor", fanout=1, rumor_k=2, rumors=2)
    topo = G.erdos_renyi(n, 0.03, seed=5)
    run = RunConfig(seed=7, max_rounds=20)
    covs1, hots1, msgs1, fin1 = simulate_curve_rumor(proto, topo, run)
    covs8, hots8, msgs8, fin8 = simulate_curve_rumor_sharded(
        proto, topo, run, make_mesh(8))
    np.testing.assert_allclose(np.asarray(covs8), np.asarray(covs1),
                               rtol=0, atol=1e-6)
    np.testing.assert_allclose(np.asarray(hots8), np.asarray(hots1),
                               rtol=0, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(msgs8), np.asarray(msgs1))
    np.testing.assert_array_equal(np.asarray(fin8.seen)[:n],
                                  np.asarray(fin1.seen))

    from gossip_tpu.backend import run_jax
    from gossip_tpu.config import MeshConfig, TopologyConfig
    rep = run_jax(proto, TopologyConfig(family="erdos_renyi", n=n,
                                        p=0.03, seed=5),
                  RunConfig(seed=7, max_rounds=20), None,
                  MeshConfig(n_devices=8), want_curve=True)
    np.testing.assert_allclose(rep.curve, np.asarray(covs1), rtol=0,
                               atol=1e-6)
