"""Artifact provenance gate (tools/validate_artifacts.py, tier-1):
every committed artifacts/*.json(l) parses, and every new-format
artifact carries the one provenance schema (run_id/git_commit/
captured — utils/telemetry.provenance).  Legacy pre-ledger artifacts
are allowlisted BY NAME, never silently grandfathered."""

import importlib.util
import json
import os

from gossip_tpu.utils import telemetry

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_spec = importlib.util.spec_from_file_location(
    "validate_artifacts",
    os.path.join(_REPO, "tools", "validate_artifacts.py"))
va = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(va)


def test_repo_artifacts_all_valid():
    """The actual gate: the committed artifacts directory is green.  A
    failure here means someone added an artifact without provenance
    (embed utils/telemetry.provenance()) or corrupted one."""
    failures = va.validate_dir(os.path.join(_REPO, "artifacts"))
    assert failures == {}, failures


def test_legacy_allowlist_names_only_committed_files():
    """The allowlist can only SHRINK: every name on it must still exist
    (a retired artifact must leave the list, keeping it an honest
    census of the pre-ledger debt)."""
    art = os.path.join(_REPO, "artifacts")
    missing = [n for n in va.LEGACY
               if not os.path.exists(os.path.join(art, n))]
    assert missing == [], missing


def test_new_json_requires_provenance(tmp_path):
    bad = tmp_path / "new_capture_r99.json"
    bad.write_text(json.dumps({"value": 1}))
    assert any("provenance" in p for p in va.validate_file(str(bad)))
    good = tmp_path / "good_capture_r99.json"
    good.write_text(json.dumps({"value": 1,
                                "provenance": telemetry.provenance()}))
    assert va.validate_file(str(good)) == []
    # top-level keys (the bench last_tpu style) also satisfy the schema
    flat = tmp_path / "flat_r99.json"
    flat.write_text(json.dumps({"run_id": "x", "git_commit": None,
                                "captured": "2026-01-01", "value": 2}))
    assert va.validate_file(str(flat)) == []


def test_new_jsonl_requires_provenance_line_and_ledgers_pass(tmp_path):
    bare = tmp_path / "rows_r99.jsonl"
    bare.write_text('{"round": 1}\n{"round": 2}\n')
    assert any("provenance" in p for p in va.validate_file(str(bare)))
    led_path = tmp_path / "ledger_x.jsonl"
    with telemetry.Ledger(str(led_path)) as led:
        led.event("probe", outcome="ok")
    assert va.validate_file(str(led_path)) == []
    # the crash contract carries over: torn lines (a killed writer —
    # tail for single-writer files, mid-file for shared ones) are
    # dropped, and the surviving lines still satisfy provenance
    with open(led_path, "a") as f:
        f.write('{"ev": "torn')
    assert va.validate_file(str(led_path)) == []
    lines = [ln for ln in led_path.read_text().splitlines()
             if ln.strip()]
    shared = tmp_path / "shared_r99.jsonl"
    shared.write_text(lines[0] + "\nTORN_CHILD_FRAGMENT\n"
                      + "\n".join(lines[1:]) + "\n")
    assert va.validate_file(str(shared)) == []
    # but a file whose PARSEABLE lines lack provenance still fails
    noprov = tmp_path / "noprov_r99.jsonl"
    noprov.write_text('TORN\n{"round": 1}\n')
    assert any("provenance" in p for p in va.validate_file(str(noprov)))


def test_malformed_json_fails_even_when_legacy(tmp_path):
    """Legacy exempts a file from provenance, never from parsing."""
    # a .json legacy name: a one-line bad .jsonl would be dropped as a
    # legal torn tail, which is the crash contract, not a parse pass
    legacy_name = sorted(n for n in va.LEGACY if n.endswith(".json"))[0]
    p = tmp_path / legacy_name
    p.write_text("{not json")
    assert any("parse" in msg for msg in va.validate_file(str(p)))


def test_validate_dir_and_main(tmp_path):
    (tmp_path / "ok_r99.json").write_text(
        json.dumps({"provenance": telemetry.provenance()}))
    (tmp_path / "bad_r99.json").write_text(json.dumps({"v": 1}))
    (tmp_path / "ignored.txt").write_text("not json, out of scope")
    failures = va.validate_dir(str(tmp_path))
    assert set(failures) == {"bad_r99.json"}
    assert va.main([str(tmp_path)]) == 1
    os.remove(tmp_path / "bad_r99.json")
    assert va.main([str(tmp_path)]) == 0


def test_round_metrics_artifacts_must_be_attributable(tmp_path):
    """A jsonl carrying ``round_metrics`` events (ops/round_metrics)
    without provenance fails EVEN under a legacy-allowlisted name —
    round metrics post-date the ledger, so the allowlist can never
    grandfather one in."""
    rm_line = json.dumps({"ev": "round_metrics", "driver": "x",
                          "rounds": 2, "totals": {"msgs": 4.0}})
    # legacy-NAMED file smuggling round metrics: still flagged
    legacy_name = sorted(va.LEGACY)[0].replace(".json", ".jsonl") \
        if not sorted(va.LEGACY)[0].endswith(".jsonl") \
        else sorted(va.LEGACY)[0]
    smuggled = tmp_path / legacy_name
    smuggled.write_text(rm_line + "\n")
    problems = va.validate_file(str(smuggled))
    assert any("round_metrics" in p for p in problems), problems

    # a proper ledger-written file with metrics passes
    good = tmp_path / "ledger_metrics_r99.jsonl"
    with telemetry.Ledger(str(good)) as led:
        led.event("round_metrics", driver="x", rounds=2,
                  totals={"msgs": 4.0})
    assert va.validate_file(str(good)) == []


def test_serving_artifacts_must_be_attributable(tmp_path):
    """A ``*serving*``/``*load*`` artifact without provenance fails —
    throughput/latency gate evidence (tools/load_harness) can never be
    grandfathered, jsonl or json alike."""
    bad = tmp_path / "ledger_serving_r99.jsonl"
    bad.write_text(json.dumps({"ev": "serving_gate", "ok": True})
                   + "\n")
    problems = va.validate_file(str(bad))
    assert any("provenance" in p for p in problems), problems

    badj = tmp_path / "load_summary_r99.json"
    badj.write_text(json.dumps({"ok": True}))
    problems = va.validate_file(str(badj))
    assert any("provenance" in p for p in problems), problems

    good = tmp_path / "ledger_serving_r98.jsonl"
    with telemetry.Ledger(str(good)) as led:
        led.event("serving_gate", ok=True, throughput_ratio=4.2)
    assert va.validate_file(str(good)) == []


def test_meshserve_artifacts_must_be_attributable(tmp_path):
    """A ``*meshserve*`` artifact without provenance fails — the
    mesh-sharded device-scaling capture (load_harness --mesh-devices)
    is the PR's headline evidence and can never be grandfathered,
    jsonl or json alike."""
    bad = tmp_path / "ledger_meshserve_r99.jsonl"
    bad.write_text(json.dumps({"ev": "meshserve_gate", "ok": True})
                   + "\n")
    problems = va.validate_file(str(bad))
    assert any("provenance" in p for p in problems), problems

    badj = tmp_path / "meshserve_summary_r99.json"
    badj.write_text(json.dumps({"ok": True}))
    problems = va.validate_file(str(badj))
    assert any("provenance" in p for p in problems), problems

    good = tmp_path / "ledger_meshserve_r98.jsonl"
    with telemetry.Ledger(str(good)) as led:
        led.event("meshserve_gate", ok=True, devices_ratio=1.1)
    assert va.validate_file(str(good)) == []


def test_crashloop_artifacts_must_be_attributable(tmp_path):
    """A ``*crashloop*`` artifact without provenance fails — the
    SIGKILL/resume record (tools/crashloop.py) is robustness evidence
    and can never be grandfathered, jsonl or json alike."""
    bad = tmp_path / "ledger_crashloop_r99.jsonl"
    bad.write_text(json.dumps({"ev": "verdict", "ok": True}) + "\n")
    problems = va.validate_file(str(bad))
    assert any("provenance" in p for p in problems), problems

    badj = tmp_path / "crashloop_summary_r99.json"
    badj.write_text(json.dumps({"ok": True}))
    problems = va.validate_file(str(badj))
    assert any("provenance" in p for p in problems), problems

    good = tmp_path / "ledger_crashloop_r98.jsonl"
    with telemetry.Ledger(str(good)) as led:
        led.event("verdict", ok=True, kills=3)
    assert va.validate_file(str(good)) == []


def test_fleet_artifacts_must_be_attributable(tmp_path):
    """A ``*fleet*``/``*router*``/``*failover*`` artifact without
    provenance fails — the replicated-serving crashloop record
    (rpc/router + tools/fleet_crashloop) is robustness evidence and
    can never be grandfathered, jsonl or json alike."""
    for name in ("ledger_fleet_r99.jsonl", "router_caps_r99.jsonl",
                 "failover_trace_r99.jsonl"):
        bad = tmp_path / name
        bad.write_text(json.dumps({"ev": "verdict", "ok": True})
                       + "\n")
        problems = va.validate_file(str(bad))
        assert any("provenance" in p for p in problems), (name,
                                                         problems)

    badj = tmp_path / "fleet_summary_r99.json"
    badj.write_text(json.dumps({"ok": True}))
    problems = va.validate_file(str(badj))
    assert any("provenance" in p for p in problems), problems

    good = tmp_path / "ledger_fleet_r98.jsonl"
    with telemetry.Ledger(str(good)) as led:
        led.event("verdict", ok=True, kills=2)
    assert va.validate_file(str(good)) == []


def test_trace_artifacts_must_be_attributable(tmp_path):
    """A ``*trace*``/``*fleet_status*`` artifact without provenance
    fails — per-request waterfalls and fleet health snapshots
    (tools/trace_report, tools/trace_capture, `gossip_tpu fleet-status
    --out`) are observability evidence and can never be grandfathered,
    jsonl or json alike.  An unattributed waterfall LOOKS like
    per-request evidence while naming no reproducible commit."""
    for name in ("ledger_trace_r99.jsonl", "trace_join_r99.jsonl",
                 "fleet_status_r99.jsonl"):
        bad = tmp_path / name
        bad.write_text(json.dumps({"ev": "request_trace",
                                   "trace_id": "ab"}) + "\n")
        problems = va.validate_file(str(bad))
        assert any("provenance" in p for p in problems), (name,
                                                          problems)

    for name in ("trace_exemplars_r99.json", "fleet_status_r99.json"):
        badj = tmp_path / name
        badj.write_text(json.dumps({"ok": True}))
        problems = va.validate_file(str(badj))
        assert any("provenance" in p for p in problems), (name,
                                                          problems)

    good = tmp_path / "ledger_trace_r98.jsonl"
    with telemetry.Ledger(str(good)) as led:
        led.event("request_trace", trace_id="ab", source="router")
    assert va.validate_file(str(good)) == []
    goodj = tmp_path / "fleet_status_r98.json"
    goodj.write_text(json.dumps({"provenance": telemetry.provenance(),
                                 "degraded": False}))
    assert va.validate_file(str(goodj)) == []


def test_fused_sweep_artifacts_must_be_attributable(tmp_path):
    """A ``*fused_sweep*`` artifact without provenance fails — the
    fused engine's compile-amortization record
    (tools/fused_sweep_capture.py) is performance evidence and can
    never be grandfathered, jsonl or json alike."""
    bad = tmp_path / "ledger_fused_sweep_r99.jsonl"
    bad.write_text(json.dumps({"ev": "fused_sweep_record", "ok": True})
                   + "\n")
    problems = va.validate_file(str(bad))
    assert any("provenance" in p for p in problems), problems

    badj = tmp_path / "fused_sweep_summary_r99.json"
    badj.write_text(json.dumps({"ok": True}))
    problems = va.validate_file(str(badj))
    assert any("provenance" in p for p in problems), problems

    good = tmp_path / "ledger_fused_sweep_r98.jsonl"
    with telemetry.Ledger(str(good)) as led:
        led.event("fused_sweep_record", ok=True, warm_ratio=4.0)
    assert va.validate_file(str(good)) == []


def test_staticcheck_artifacts_must_be_attributable(tmp_path):
    """A ``*staticcheck*``/``*lint*`` artifact without provenance
    fails — an invariant-analyzer verdict (gossip_tpu/analysis +
    tools/staticcheck.py) certifies a specific commit's tree and can
    never be grandfathered, jsonl or json alike."""
    bad = tmp_path / "ledger_staticcheck_r99.jsonl"
    bad.write_text(json.dumps({"ev": "staticcheck",
                               "verdict": "clean"}) + "\n")
    problems = va.validate_file(str(bad))
    assert any("provenance" in p for p in problems), problems

    badl = tmp_path / "lint_summary_r99.json"
    badl.write_text(json.dumps({"verdict": "clean"}))
    problems = va.validate_file(str(badl))
    assert any("provenance" in p for p in problems), problems

    good = tmp_path / "ledger_staticcheck_r98.jsonl"
    with telemetry.artifact_ledger(str(good)) as led:
        led.event("staticcheck", verdict="clean", findings=0)
    assert va.validate_file(str(good)) == []


def test_cost_attribution_artifacts_must_be_attributable(tmp_path):
    """A ``*cost*``/``*xprof*``/``*attribution*`` artifact without
    provenance fails — XLA cost & memory attribution evidence
    (utils/compile_cache's xla_compile events via
    tools/cost_capture.py) can never be grandfathered, jsonl or json
    alike: an unattributed cost table is the exact failure the
    attribution plane exists to prevent."""
    for name in ("ledger_cost_r99.jsonl", "xprof_dump_r99.jsonl",
                 "attribution_r99.jsonl"):
        bad = tmp_path / name
        bad.write_text(json.dumps({"ev": "xla_compile",
                                   "label": "dense"}) + "\n")
        problems = va.validate_file(str(bad))
        assert any("provenance" in p for p in problems), (name,
                                                          problems)

    for name in ("cost_table_r99.json", "attribution_r99.json"):
        badj = tmp_path / name
        badj.write_text(json.dumps({"flops": 1.0}))
        problems = va.validate_file(str(badj))
        assert any("provenance" in p for p in problems), (name,
                                                          problems)

    good = tmp_path / "ledger_cost_r98.jsonl"
    with telemetry.Ledger(str(good)) as led:
        led.event("xla_compile", label="dense", cache="miss")
    assert va.validate_file(str(good)) == []
    goodj = tmp_path / "cost_table_r98.json"
    goodj.write_text(json.dumps({"provenance": telemetry.provenance(),
                                 "flops": 1.0}))
    assert va.validate_file(str(goodj)) == []


def test_scale_plan_budget_artifacts_must_be_attributable(tmp_path):
    """A ``*scale*``/``*plan*``/``*budget*`` artifact without
    provenance fails — capacity plans and streamed-tiling records
    (gossip_tpu/planner + tools/scale_capture.py) are the 100M-node
    scaling evidence and can never be grandfathered, jsonl or json
    alike.  The ONE colliding legacy name
    (dryrun_steady_budget_r06.json — the round-6 steady-wall budget
    snapshot docs/PERF.md cites) is carved out explicitly and stays on
    the ordinary legacy list."""
    for name in ("ledger_scale_r99.jsonl", "ledger_plan_r99.jsonl",
                 "hbm_budget_r99.jsonl"):
        bad = tmp_path / name
        bad.write_text(json.dumps({"ev": "scale_record", "ok": True})
                       + "\n")
        problems = va.validate_file(str(bad))
        assert any("provenance" in p for p in problems), (name,
                                                          problems)

    badj = tmp_path / "scale_plan_r99.json"
    badj.write_text(json.dumps({"tiles": 4}))
    problems = va.validate_file(str(badj))
    assert any("provenance" in p for p in problems), problems

    good = tmp_path / "ledger_scale_r98.jsonl"
    with telemetry.Ledger(str(good)) as led:
        led.event("scale_record", ok=True, tiles=4)
    assert va.validate_file(str(good)) == []

    # the carve-out: matcher-excluded by exact name, still legacy-
    # allowlisted — and the committed file still parses
    assert not va._is_scale_name("dryrun_steady_budget_r06.json")
    assert va._is_scale_name("dryrun_steady_budget_r07.json")
    committed = os.path.join(va.REPO, "artifacts",
                             "dryrun_steady_budget_r06.json")
    assert va.validate_file(committed) == []
