"""Dry-run contract: schema + steady-state budget guard (tier-1).

``__graft_entry__.dryrun_multichip`` is the driver's MULTICHIP record;
its per-family table is how collective-layout and driver-cache
regressions surface round-over-round.  This test pins the contract so
the schema (all 10 families, the wall-decomposition keys on the fused
rows) and the per-family steady budgets (tools/dryrun_budgets.json —
the guard that catches the next 100x outlier at PR time) cannot
silently regress.  The dry run re-execs itself in a hermetic scrubbed
subprocess, so this is safe on any ambient platform.
"""

import importlib.util
import os

import pytest

from gossip_tpu.utils import telemetry

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# repo-root module, not a package member: load by path so collection
# works from any cwd (same pattern as test_bench_contract.py)
_spec = importlib.util.spec_from_file_location(
    "graft_entry", os.path.join(_REPO, "__graft_entry__.py"))
graft_entry = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(graft_entry)

_rspec = importlib.util.spec_from_file_location(
    "telemetry_report", os.path.join(_REPO, "tools",
                                     "telemetry_report.py"))
telemetry_report = importlib.util.module_from_spec(_rspec)
_rspec.loader.exec_module(telemetry_report)

FAMILIES = frozenset({
    "dense_pushpull", "packed_pull", "sparse_antientropy",
    "topo_sparse_antientropy", "swim_rotating", "halo_banded",
    "fused_planes", "fused_planes_fault_curve", "rumor_sir",
    "hybrid_2d_sweep"})
DECOMPOSED = ("fused_planes", "fused_planes_fault_curve")
DECOMP_KEYS = ("steady_exec_ms", "init_build_ms", "driver_overhead_ms")


def test_budget_file_parses_and_covers_every_family():
    budgets = graft_entry.dryrun_steady_budgets()
    assert set(budgets) == FAMILIES
    assert all(v > 0 for v in budgets.values())


def test_dryrun_carries_all_families_and_wall_decomposition(tmp_path):
    """One real dry run on a 4-device hermetic CPU mesh: every family
    present with first/steady timings, the fused rows wall-decomposed,
    and the in-body budget guard green (a budget trip raises through
    dryrun_multichip's subprocess rc check).

    Since round 7 the same run is also the telemetry contract: the
    budget guard runs with the ledger ENABLED (so a green guard
    certifies telemetry adds no steady-state cost), and the per-family
    table must be reproducible from ledger data alone
    (tools/telemetry_report.family_table == the stdout table)."""
    ledger_path = str(tmp_path / "dryrun_ledger.jsonl")
    out = graft_entry.dryrun_multichip(4, ledger_path=ledger_path)
    fam = out["dryrun_family_ms"]
    assert set(fam) == FAMILIES
    for name, row in fam.items():
        assert row["first_ms"] > 0, name
        assert row["steady_ms"] > 0, name
    for name in DECOMPOSED:
        row = fam[name]
        for key in DECOMP_KEYS:
            assert key in row, (name, key)
        # the decomposition reconciles: steady ~= exec + init + residual
        total = (row["steady_exec_ms"] + row["init_build_ms"]
                 + row["driver_overhead_ms"])
        assert total == pytest.approx(row["steady_ms"], abs=0.5), name

    # --- the run ledger reproduces the table from its own data alone
    assert out["ledger_path"] == ledger_path
    events = telemetry.load_ledger(ledger_path, run="last")
    assert events[0]["ev"] == "provenance"
    assert any(e["ev"] == "runtime" and e["device_count"] == 4
               for e in events)
    assert telemetry_report.family_table(events) == fam
    # one span per family timing, all closed, rooted under the run span
    tree = telemetry_report.span_tree(events)
    names = {n["name"] for _, n in tree}
    assert "dryrun_multichip" in names
    for name in FAMILIES:
        assert f"{name}:first_ms" in names
        assert f"{name}:steady_ms" in names
    assert not [n["name"] for _, n in tree if n["unclosed"]]
    # the guard verdict is ledgered (green — telemetry was on)
    guard = [e for e in events if e["ev"] == "budget_guard"][-1]
    assert guard["ok"] is True
    # and the markdown render carries every family row + the verdict
    md = telemetry_report.render_markdown(events)
    for name in FAMILIES:
        assert name in md
    assert "green" in md


def test_committed_8dev_dryrun_ledger_renders():
    """The committed 8-device dry-run ledger artifact
    (artifacts/ledger_dryrun_r07.jsonl) is the doc-ready record: it
    must keep parsing, carry provenance, and render the full
    per-family table (first/steady/decomposition) from ledger data
    alone."""
    path = os.path.join(_REPO, "artifacts", "ledger_dryrun_r07.jsonl")
    events = telemetry.load_ledger(path, run="last")
    prov = events[0]
    assert prov["ev"] == "provenance"
    assert len(prov["git_commit"]) == 40
    assert any(e["ev"] == "runtime" and e["device_count"] == 8
               for e in events)
    fam = telemetry_report.family_table(events)
    assert set(fam) == FAMILIES
    for name in DECOMPOSED:
        for key in DECOMP_KEYS:
            assert key in fam[name], (name, key)
    budgets = graft_entry.dryrun_steady_budgets()
    assert all(fam[f]["steady_ms"] <= budgets[f] for f in fam)
    md = telemetry_report.render_markdown(events)
    for name in FAMILIES:
        assert name in md
    assert "budget_ms" in md and "steady_exec_ms" in md
