"""Dry-run contract: schema + steady-state budget guard (tier-1).

``__graft_entry__.dryrun_multichip`` is the driver's MULTICHIP record;
its per-family table is how collective-layout and driver-cache
regressions surface round-over-round.  This test pins the contract so
the schema (all 10 families, the wall-decomposition keys on the fused
rows) and the per-family steady budgets (tools/dryrun_budgets.json —
the guard that catches the next 100x outlier at PR time) cannot
silently regress.  The dry run re-execs itself in a hermetic scrubbed
subprocess, so this is safe on any ambient platform.
"""

import importlib.util
import os

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# repo-root module, not a package member: load by path so collection
# works from any cwd (same pattern as test_bench_contract.py)
_spec = importlib.util.spec_from_file_location(
    "graft_entry", os.path.join(_REPO, "__graft_entry__.py"))
graft_entry = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(graft_entry)

FAMILIES = frozenset({
    "dense_pushpull", "packed_pull", "sparse_antientropy",
    "topo_sparse_antientropy", "swim_rotating", "halo_banded",
    "fused_planes", "fused_planes_fault_curve", "rumor_sir",
    "hybrid_2d_sweep"})
DECOMPOSED = ("fused_planes", "fused_planes_fault_curve")
DECOMP_KEYS = ("steady_exec_ms", "init_build_ms", "driver_overhead_ms")


def test_budget_file_parses_and_covers_every_family():
    budgets = graft_entry.dryrun_steady_budgets()
    assert set(budgets) == FAMILIES
    assert all(v > 0 for v in budgets.values())


def test_dryrun_carries_all_families_and_wall_decomposition():
    """One real dry run on a 4-device hermetic CPU mesh: every family
    present with first/steady timings, the fused rows wall-decomposed,
    and the in-body budget guard green (a budget trip raises through
    dryrun_multichip's subprocess rc check)."""
    out = graft_entry.dryrun_multichip(4)
    fam = out["dryrun_family_ms"]
    assert set(fam) == FAMILIES
    for name, row in fam.items():
        assert row["first_ms"] > 0, name
        assert row["steady_ms"] > 0, name
    for name in DECOMPOSED:
        row = fam[name]
        for key in DECOMP_KEYS:
            assert key in row, (name, key)
        # the decomposition reconciles: steady ~= exec + init + residual
        total = (row["steady_exec_ms"] + row["init_build_ms"]
                 + row["driver_overhead_ms"])
        assert total == pytest.approx(row["steady_ms"], abs=0.5), name
