"""Dry-run contract: schema + steady-state budget + warm-start guard
(tier-1).

``__graft_entry__.dryrun_multichip`` is the driver's MULTICHIP record;
its per-family table is how collective-layout and driver-cache
regressions surface round-over-round.  This test pins the contract so
the schema (all 10 families, the wall-decomposition keys on the fused
rows) and the per-family steady budgets (tools/dryrun_budgets.json —
the guard that catches the next 100x outlier at PR time) cannot
silently regress.  The dry run re-execs itself in a hermetic scrubbed
subprocess, so this is safe on any ambient platform.

Since the compile-once PR the SAME pair of runs is also the warm-start
contract: the module fixture runs the dry run twice against one fresh
compile-cache dir — process A populates it cold, process B must reuse
it — so the cross-process cache proof, the ``first_warm_ms`` budget
guard, and the ledger's per-family ``compile`` events (cache:
hit|miss|disabled) are all exercised by tier-1 on every PR.
"""

import importlib.util
import os

import pytest

from gossip_tpu.utils import telemetry

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# repo-root module, not a package member: load by path so collection
# works from any cwd (same pattern as test_bench_contract.py)
_spec = importlib.util.spec_from_file_location(
    "graft_entry", os.path.join(_REPO, "__graft_entry__.py"))
graft_entry = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(graft_entry)

_rspec = importlib.util.spec_from_file_location(
    "telemetry_report", os.path.join(_REPO, "tools",
                                     "telemetry_report.py"))
telemetry_report = importlib.util.module_from_spec(_rspec)
_rspec.loader.exec_module(telemetry_report)

_tspec = importlib.util.spec_from_file_location(
    "readme_table", os.path.join(_REPO, "tools", "readme_table.py"))
readme_table = importlib.util.module_from_spec(_tspec)
_tspec.loader.exec_module(readme_table)

FAMILIES = frozenset({
    "dense_pushpull", "churn_heal", "churn_sweep", "fused_churn_sweep",
    "crdt_counter", "kafka_log", "txn_register", "serving_batch",
    "mesh_serving", "fleet_failover", "request_trace", "packed_pull",
    "scale_plan", "scale_stream_overlap", "sparse_antientropy",
    "topo_sparse_antientropy", "swim_rotating", "halo_banded",
    "fused_planes", "fused_planes_fault_curve", "rumor_sir",
    "hybrid_2d_sweep", "cost_attribution", "byzantine_conv"})
# the committed r24 record predates the byzantine-nemesis PR's
# byzantine_conv family; the committed r23 record predates the
# observability PR's
# cost_attribution family; the committed r22 record predates the
# pipelined-streaming PR's
# scale_stream_overlap family; the committed r21 record predates the
# tracing PR's request_trace
# family; the committed r20 record predates the mesh-serving PR's mesh_serving
# family; the committed r18 record predates the scale-planner PR's scale_plan
# family; the committed r17 record additionally predates the fleet
# PR's fleet_failover
# family; the committed r16 record additionally predates the
# fused-operand PR's fused_churn_sweep family; the committed r15
# record additionally predates the transactions PR's txn_register
# family; the committed r14 record additionally predates the
# replicated-log PR's kafka_log family; the committed r13 record
# additionally predates the serving PR's serving_batch family; the
# committed r11 record additionally predates the CRDT PR's
# crdt_counter family; the committed r07/r08/r09 records additionally
# predate the compiled-nemesis PR's churn_heal family and the
# traced-operand PR's churn_sweep family — each pin stays on its
# historical set
FAMILIES_PRE_BYZ = FAMILIES - {"byzantine_conv"}
FAMILIES_PRE_COST = FAMILIES_PRE_BYZ - {"cost_attribution"}
FAMILIES_PRE_OVERLAP = FAMILIES_PRE_COST - {"scale_stream_overlap"}
FAMILIES_PRE_TRACE = FAMILIES_PRE_OVERLAP - {"request_trace"}
FAMILIES_PRE_MESH = FAMILIES_PRE_TRACE - {"mesh_serving"}
FAMILIES_PRE_SCALE = FAMILIES_PRE_MESH - {"scale_plan"}
FAMILIES_PRE_FLEET = FAMILIES_PRE_SCALE - {"fleet_failover"}
FAMILIES_PRE_FUSED_SWEEP = FAMILIES_PRE_FLEET - {"fused_churn_sweep"}
FAMILIES_PRE_TXN = FAMILIES_PRE_FUSED_SWEEP - {"txn_register"}
FAMILIES_PRE_LOG = FAMILIES_PRE_TXN - {"kafka_log"}
FAMILIES_PRE_SERVING = FAMILIES_PRE_LOG - {"serving_batch"}
FAMILIES_PRE_CRDT = FAMILIES_PRE_SERVING - {"crdt_counter"}
FAMILIES_PRE_CHURN = FAMILIES_PRE_CRDT - {"churn_heal", "churn_sweep"}
DECOMPOSED = ("fused_planes", "fused_planes_fault_curve")
DECOMP_KEYS = ("steady_exec_ms", "init_build_ms", "driver_overhead_ms")


def test_budget_file_parses_and_covers_every_family():
    steady = graft_entry.dryrun_steady_budgets()
    warm = graft_entry.dryrun_first_warm_budgets()
    assert set(steady) == FAMILIES
    assert set(warm) == FAMILIES
    assert all(v > 0 for v in steady.values())
    assert all(v > 0 for v in warm.values())


# The (cold, warm) 4-device dry-run pair is the SESSION-scoped
# ``dryrun_pair`` fixture in tests/conftest.py since the observability
# PR: one pair now serves both this module's contract tests and the
# ledger_diff regression gate (tests/test_ledger_diff.py).


def test_dryrun_carries_all_families_and_wall_decomposition(dryrun_pair):
    """One real dry run on a 4-device hermetic CPU mesh: every family
    present with first/steady timings, the fused rows wall-decomposed,
    and the in-body budget guard green (a budget trip raises through
    dryrun_multichip's subprocess rc check).

    Since round 7 the same run is also the telemetry contract: the
    budget guard runs with the ledger ENABLED (so a green guard
    certifies telemetry adds no steady-state cost), and the per-family
    table must be reproducible from ledger data alone
    (tools/telemetry_report.family_table == the stdout table)."""
    out = dryrun_pair["cold"]
    fam = out["dryrun_family_ms"]
    assert set(fam) == FAMILIES
    for name, row in fam.items():
        assert row["first_ms"] > 0, name
        assert row["steady_ms"] > 0, name
    for name in DECOMPOSED:
        row = fam[name]
        for key in DECOMP_KEYS:
            assert key in row, (name, key)
        # the decomposition reconciles: steady ~= exec + init + residual
        total = (row["steady_exec_ms"] + row["init_build_ms"]
                 + row["driver_overhead_ms"])
        assert total == pytest.approx(row["steady_ms"], abs=0.5), name

    # --- the run ledger reproduces the table from its own data alone
    events = telemetry.load_ledger(out["ledger_path"], run="last")
    assert events[0]["ev"] == "provenance"
    assert any(e["ev"] == "runtime" and e["device_count"] == 4
               for e in events)
    assert telemetry_report.family_table(events) == fam
    # one span per family timing, all closed, rooted under the run span
    tree = telemetry_report.span_tree(events)
    names = {n["name"] for _, n in tree}
    assert "dryrun_multichip" in names
    for name in FAMILIES:
        assert f"{name}:first_ms" in names
        assert f"{name}:steady_ms" in names
    assert not [n["name"] for _, n in tree if n["unclosed"]]
    # the guard verdict is ledgered (green — telemetry was on)
    guard = [e for e in events if e["ev"] == "budget_guard"][-1]
    assert guard["ok"] is True
    # and the markdown render carries every family row + the verdict
    md = telemetry_report.render_markdown(events)
    for name in FAMILIES:
        assert name in md
    assert "green" in md


def test_dryrun_warm_process_reuses_cold_process_cache(dryrun_pair):
    """THE compile-once contract pair: process B's aggregate
    first-call wall must be far below process A's (the body already
    enforced the per-family first_warm_ms budgets via expect_warm —
    this asserts the headline ratio on the same data).  The LIVE
    threshold is 2.0x: a de-warmed cache reads ~1.0x unambiguously,
    while the 4-device pair's honest ratio is only ~2.8x (smaller mesh
    = cheaper cold compiles over the same warm trace cost) and host
    contention inflates the warm column's fixed costs slightly more;
    the exact >= 3x acceptance is pinned on the committed 8-device r08
    record below, where there is no host noise.  Trajectories must be
    BITWISE unaffected by where the executables came from (identical
    per-family tables modulo walls is necessary; the value-level
    equality is pinned driver-by-driver in
    tests/test_compile_cache.py)."""
    cold_fam = dryrun_pair["cold"]["dryrun_family_ms"]
    warm_fam = dryrun_pair["warm"]["dryrun_family_ms"]
    assert set(warm_fam) == set(cold_fam) == FAMILIES
    cold_total = sum(r["first_ms"] for r in cold_fam.values())
    warm_total = sum(r["first_ms"] for r in warm_fam.values())
    assert warm_total * 2.0 <= cold_total, (
        f"warm-start win below 2x: cold {cold_total:.0f} ms vs warm "
        f"{warm_total:.0f} ms — the persistent cache did not serve "
        "the warm process")
    # the cache dir actually holds the executables both layers wrote
    assert os.path.isdir(dryrun_pair["cache"])
    assert any(os.scandir(dryrun_pair["cache"]))

    # --- ledger: per-family compile events carry the cache verdict
    def compile_events(out):
        evs = telemetry.load_ledger(out["ledger_path"], run="last")
        return evs, [e for e in evs if e["ev"] == "compile"
                     and e.get("phase") == "first_ms"]

    cold_evs, cold_compiles = compile_events(dryrun_pair["cold"])
    warm_evs, warm_compiles = compile_events(dryrun_pair["warm"])
    assert {e["family"] for e in cold_compiles} == FAMILIES
    assert {e["family"] for e in warm_compiles} == FAMILIES
    # process A pays real compiles; process B is served by the cache.
    # request_trace is host-only by design — zero compiles of its own
    # is the family's whole point (the batcher reuses serving_batch's
    # executables), so its compile event says cache="none" in BOTH
    # processes and sits outside the miss->hit proof.  A warm
    # cost_attribution is served by the AOT chokepoint store, which
    # the plain-jit persistent-cache monitor cannot see (cache="none"
    # in the warm process) — its own miss->hit proof is the
    # chokepoint's xla_compile verdicts, asserted below.
    assert all(e["cache"] == "miss" for e in cold_compiles
               if e["family"] != "request_trace")
    assert all(e["cache"] == "hit" for e in warm_compiles
               if e["family"] not in ("request_trace",
                                      "cost_attribution")), [
        (e["family"], e["cache"]) for e in warm_compiles
        if e["cache"] != "hit"]
    assert all(e["cache"] == "none"
               for e in cold_compiles + warm_compiles
               if e["family"] == "request_trace")
    # the chokepoint family's cross-process warm proof, on its own
    # attribution events: cold (miss, hit), warm (hit, hit)
    for evs, want in ((cold_evs, ["miss", "hit"]),
                      (warm_evs, ["hit", "hit"])):
        assert [e["cache"] for e in evs if e["ev"] == "xla_compile"
                and e.get("label") == "cost_probe"] == want
    # the enable event recorded the shared dir in both ledgers
    for evs in (cold_evs, warm_evs):
        cc = [e for e in evs if e["ev"] == "compile_cache"]
        assert cc and cc[-1]["dir"] == os.path.abspath(
            dryrun_pair["cache"])
        assert cc[-1]["persistent"] is True
    # the warm guard's verdict is ledgered green
    wguard = [e for e in warm_evs if e["ev"] == "budget_guard"
              and e.get("phase") == "first_warm"][-1]
    assert wguard["ok"] is True
    # and the report's cache table renders both verdicts
    assert "miss" in telemetry_report.render_markdown(cold_evs)
    warm_md = telemetry_report.render_markdown(warm_evs)
    assert "## Compile cache" in warm_md and "hit" in warm_md


def test_committed_8dev_dryrun_ledger_renders():
    """The committed 8-device dry-run ledger artifact
    (artifacts/ledger_dryrun_r07.jsonl) is the doc-ready record: it
    must keep parsing, carry provenance, and render the full
    per-family table (first/steady/decomposition) from ledger data
    alone."""
    path = os.path.join(_REPO, "artifacts", "ledger_dryrun_r07.jsonl")
    events = telemetry.load_ledger(path, run="last")
    prov = events[0]
    assert prov["ev"] == "provenance"
    assert len(prov["git_commit"]) == 40
    assert any(e["ev"] == "runtime" and e["device_count"] == 8
               for e in events)
    fam = telemetry_report.family_table(events)
    assert set(fam) == FAMILIES_PRE_CHURN
    for name in DECOMPOSED:
        for key in DECOMP_KEYS:
            assert key in fam[name], (name, key)
    budgets = graft_entry.dryrun_steady_budgets()
    assert all(fam[f]["steady_ms"] <= budgets[f] for f in fam)
    md = telemetry_report.render_markdown(events)
    for name in FAMILIES_PRE_CHURN:
        assert name in md
    assert "budget_ms" in md and "steady_exec_ms" in md


def test_committed_warmstart_ledger_renders_cache_table():
    """The committed warm-start record
    (artifacts/ledger_dryrun_r08.jsonl): TWO 8-device runs in one
    flight-recorder file — run 1 cold into a fresh cache, run 2 warm
    from it.  Pins that (a) the warm run met the first_warm_ms budgets
    and beat the cold run's aggregate >= 3x (the acceptance line, on
    committed evidence), (b) every family timing carries a ``compile``
    event with the cache verdict, and (c) the report renders the
    hit/miss table from the artifact alone."""
    path = os.path.join(_REPO, "artifacts", "ledger_dryrun_r08.jsonl")
    all_events = telemetry.load_ledger(path)
    run_ids = telemetry_report.runs(all_events)
    assert len(run_ids) == 2, "expect exactly a cold and a warm run"
    cold = [e for e in all_events if e.get("run") == run_ids[0]]
    warm = [e for e in all_events if e.get("run") == run_ids[1]]
    for events in (cold, warm):
        assert events[0]["ev"] == "provenance"
        assert len(events[0]["git_commit"]) == 40
        assert any(e["ev"] == "runtime" and e["device_count"] == 8
                   for e in events)
        assert set(telemetry_report.family_table(events)) \
            == FAMILIES_PRE_CHURN
    cold_fam = telemetry_report.family_table(cold)
    warm_fam = telemetry_report.family_table(warm)
    cold_total = sum(r["first_ms"] for r in cold_fam.values())
    warm_total = sum(r["first_ms"] for r in warm_fam.values())
    assert warm_total * 3 <= cold_total
    wbudgets = graft_entry.dryrun_first_warm_budgets()
    assert all(warm_fam[f]["first_ms"] <= wbudgets[f] for f in warm_fam)
    # cache verdicts: all-miss cold, all-hit warm
    cold_cache = telemetry_report.compile_cache_table(cold)
    warm_cache = telemetry_report.compile_cache_table(warm)
    assert cold_cache["status"]["persistent"] is True
    assert {r["where"] for r in cold_cache["rows"]
            if r["phase"] == "first_ms"} == FAMILIES_PRE_CHURN
    assert all(r["cache"] == "miss" for r in cold_cache["rows"]
               if r["phase"] == "first_ms")
    assert all(r["cache"] == "hit" for r in warm_cache["rows"]
               if r["phase"] == "first_ms")
    md = telemetry_report.render_markdown(warm)
    assert "## Compile cache" in md
    assert "| hit " in md          # per-family verdict rows rendered
    # the headline event made it too
    totals = [e for e in warm if e["ev"] == "first_ms_total"]
    assert totals and totals[-1]["total_ms"] == pytest.approx(
        warm_total, abs=1.0)
    # and the docs/PERF.md cold/warm budget table renders from the
    # artifact alone (tools/readme_table.py --first-budgets)
    import contextlib
    import io
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = readme_table.main_first_budgets([path])
    assert rc == 0
    table = buf.getvalue()
    assert "first_warm_budget_ms" in table
    for fam in FAMILIES_PRE_CHURN:
        assert fam in table
    assert "**total**" in table


def test_committed_r09_record_budgets_hold_with_round_metrics_on():
    """The observability-PR record (artifacts/ledger_dryrun_r09.jsonl):
    two 8-device runs captured WITH the device-resident round-metrics
    plane active.  Pins that (a) the steady budgets and the warm-start
    acceptance (warm first-call aggregate >= 3x under cold) still hold
    with metrics on — the committed zero-cost proof — and (b) the
    driver-level families ledgered their ``round_metrics`` stacks, and
    the report renders them as the Protocol metrics section."""
    path = os.path.join(_REPO, "artifacts", "ledger_dryrun_r09.jsonl")
    all_events = telemetry.load_ledger(path)
    run_ids = telemetry_report.runs(all_events)
    assert len(run_ids) == 2
    cold = [e for e in all_events if e.get("run") == run_ids[0]]
    warm = [e for e in all_events if e.get("run") == run_ids[1]]
    for events in (cold, warm):
        assert events[0]["ev"] == "provenance"
        assert any(e["ev"] == "runtime" and e["device_count"] == 8
                   for e in events)
        assert set(telemetry_report.family_table(events)) \
            == FAMILIES_PRE_CHURN
        guard = [e for e in events if e["ev"] == "budget_guard"
                 and "phase" not in e][-1]
        assert guard["ok"] is True
        # the driver-level families flushed their round-metric stacks
        drivers = {e.get("driver") for e in events
                   if e.get("ev") == "round_metrics"}
        assert {"simulate_until_sharded_fused",
                "simulate_curve_sharded_fused"} <= drivers
        for e in events:
            if e.get("ev") != "round_metrics":
                continue
            assert e["rounds"] == 2 and e["shards"] == 8
            for series in ("newly", "dup", "msgs", "bytes"):
                assert len(e[series]) == 2
            # the zero-ICI claim, checkable per round: the fused plane
            # drivers' only cross-device traffic is the scalar
            # coverage reduction
            assert all(b <= 8.0 for b in e["bytes"])
    cold_fam = telemetry_report.family_table(cold)
    warm_fam = telemetry_report.family_table(warm)
    cold_total = sum(r["first_ms"] for r in cold_fam.values())
    warm_total = sum(r["first_ms"] for r in warm_fam.values())
    assert warm_total * 3 <= cold_total
    wbudgets = graft_entry.dryrun_first_warm_budgets()
    assert all(warm_fam[f]["first_ms"] <= wbudgets[f] for f in warm_fam)
    md = telemetry_report.render_markdown(warm)
    assert "## Protocol metrics" in md
    assert "simulate_until_sharded_fused" in md
    # ledger health: the CI --check gate passes on the committed record
    assert telemetry_report.check_health(cold) == []
    assert telemetry_report.check_health(warm) == []


def test_committed_r11_4dev_record_carries_churn_sweep():
    """The traced-operand PR's committed 4-device record
    (artifacts/ledger_dryrun_r11_4dev.jsonl): cold+warm pair on its
    historical family set — churn_heal and churn_sweep included,
    crdt_counter not yet — warm run all-hit, budgets held, provenance
    present.  (The live ledger_diff gate baseline moved to the r13
    record below when the CRDT PR grew the family set.)"""
    path = os.path.join(_REPO, "artifacts",
                        "ledger_dryrun_r11_4dev.jsonl")
    all_events = telemetry.load_ledger(path)
    run_ids = telemetry_report.runs(all_events)
    assert len(run_ids) == 2
    cold = [e for e in all_events if e.get("run") == run_ids[0]]
    warm = [e for e in all_events if e.get("run") == run_ids[1]]
    for events in (cold, warm):
        assert events[0]["ev"] == "provenance"
        assert len(events[0]["git_commit"]) == 40
        assert any(e["ev"] == "runtime" and e["device_count"] == 4
                   for e in events)
        assert set(telemetry_report.family_table(events)) \
            == FAMILIES_PRE_CRDT
    warm_fam = telemetry_report.family_table(warm)
    budgets = graft_entry.dryrun_steady_budgets()
    assert all(warm_fam[f]["steady_ms"] <= budgets[f] for f in warm_fam)
    wbudgets = graft_entry.dryrun_first_warm_budgets()
    assert all(warm_fam[f]["first_ms"] <= wbudgets[f] for f in warm_fam)
    assert all(e["cache"] == "hit" for e in warm
               if e.get("ev") == "compile"
               and e.get("phase") == "first_ms")
    # the whole warm family set reuses the cold process's executables:
    # the warm-start win holds with the sweep family included
    cold_fam = telemetry_report.family_table(cold)
    cold_total = sum(r["first_ms"] for r in cold_fam.values())
    warm_total = sum(r["first_ms"] for r in warm_fam.values())
    assert warm_total * 3 <= cold_total


def _assert_cold_warm_record(path, families, host_only=frozenset()):
    """The committed 4-device cold+warm record contract the r13 and
    r14 pins share: two provenance-stamped runs, the given family set,
    warm run all-hit, steady + warm budgets held, >= 3x warm-start
    aggregate.  ``host_only`` names families that compile nothing of
    their own (request_trace) — their compile events carry
    cache="none" and sit outside the all-hit proof."""
    all_events = telemetry.load_ledger(path)
    run_ids = telemetry_report.runs(all_events)
    assert len(run_ids) == 2
    cold = [e for e in all_events if e.get("run") == run_ids[0]]
    warm = [e for e in all_events if e.get("run") == run_ids[1]]
    for events in (cold, warm):
        assert events[0]["ev"] == "provenance"
        assert len(events[0]["git_commit"]) == 40
        assert any(e["ev"] == "runtime" and e["device_count"] == 4
                   for e in events)
        assert set(telemetry_report.family_table(events)) == families
    warm_fam = telemetry_report.family_table(warm)
    budgets = graft_entry.dryrun_steady_budgets()
    assert all(warm_fam[f]["steady_ms"] <= budgets[f] for f in warm_fam)
    wbudgets = graft_entry.dryrun_first_warm_budgets()
    assert all(warm_fam[f]["first_ms"] <= wbudgets[f] for f in warm_fam)
    assert all(e["cache"] == "hit" for e in warm
               if e.get("ev") == "compile"
               and e.get("phase") == "first_ms"
               and e["family"] not in host_only)
    assert all(e["cache"] == "none" for e in warm
               if e.get("ev") == "compile"
               and e.get("phase") == "first_ms"
               and e["family"] in host_only)
    cold_fam = telemetry_report.family_table(cold)
    cold_total = sum(r["first_ms"] for r in cold_fam.values())
    warm_total = sum(r["first_ms"] for r in warm_fam.values())
    assert warm_total * 3 <= cold_total


def test_committed_r13_4dev_record_carries_crdt_counter():
    """The CRDT PR's committed 4-device record
    (artifacts/ledger_dryrun_r13_4dev.jsonl): cold+warm pair on its
    historical family set — crdt_counter included, serving_batch not
    yet.  (The live ledger_diff gate baseline moved to the r14 record
    below when the serving PR grew the family set.)"""
    _assert_cold_warm_record(
        os.path.join(_REPO, "artifacts", "ledger_dryrun_r13_4dev.jsonl"),
        FAMILIES_PRE_SERVING)


def test_committed_r14_4dev_record_carries_serving_batch():
    """The serving PR's committed 4-device record
    (artifacts/ledger_dryrun_r14_4dev.jsonl): cold+warm pair on its
    historical family set — serving_batch included, kafka_log not yet.
    (The live ledger_diff gate baseline moved to the r15 record below
    when the replicated-log PR grew the family set.)"""
    _assert_cold_warm_record(
        os.path.join(_REPO, "artifacts", "ledger_dryrun_r14_4dev.jsonl"),
        FAMILIES_PRE_LOG)


def test_committed_r15_4dev_record_carries_kafka_log():
    """The replicated-log PR's committed 4-device record
    (artifacts/ledger_dryrun_r15_4dev.jsonl): cold+warm pair on its
    historical family set — kafka_log included, txn_register not yet.
    (The live ledger_diff gate baseline moved to the r16 record below
    when the transactions PR grew the family set.)"""
    _assert_cold_warm_record(
        os.path.join(_REPO, "artifacts", "ledger_dryrun_r15_4dev.jsonl"),
        FAMILIES_PRE_TXN)


def test_committed_r16_4dev_record_carries_txn_register():
    """The transactions PR's committed 4-device record
    (artifacts/ledger_dryrun_r16_4dev.jsonl): cold+warm pair on its
    historical family set — txn_register included, fused_churn_sweep
    not yet.  (The live ledger_diff gate baseline moved to the r17
    record below when the fused-operand PR grew the family set.)"""
    _assert_cold_warm_record(
        os.path.join(_REPO, "artifacts", "ledger_dryrun_r16_4dev.jsonl"),
        FAMILIES_PRE_FUSED_SWEEP)


def test_committed_r17_4dev_record_carries_fused_churn_sweep():
    """The fused-operand PR's committed 4-device record
    (artifacts/ledger_dryrun_r17_4dev.jsonl): cold+warm pair on its
    historical family set — fused_churn_sweep included, fleet_failover
    not yet.  (The live ledger_diff gate baseline moved to the r18
    record below when the fleet PR grew the family set.)"""
    _assert_cold_warm_record(
        os.path.join(_REPO, "artifacts", "ledger_dryrun_r17_4dev.jsonl"),
        FAMILIES_PRE_FLEET)


def test_committed_r18_4dev_record_carries_fleet_failover():
    """The fleet PR's committed 4-device record
    (artifacts/ledger_dryrun_r18_4dev.jsonl): cold+warm pair on its
    historical family set — fleet_failover included, scale_plan not
    yet.  (The live ledger_diff gate baseline moved to the r20 record
    below when the scale-planner PR grew the family set.)"""
    _assert_cold_warm_record(
        os.path.join(_REPO, "artifacts", "ledger_dryrun_r18_4dev.jsonl"),
        FAMILIES_PRE_SCALE)


def test_committed_r20_4dev_record_carries_scale_plan():
    """The scale-planner PR's committed 4-device record
    (artifacts/ledger_dryrun_r20_4dev.jsonl): cold+warm pair on its
    historical family set — scale_plan included (a forced >= 2-tile
    streamed run with the bitwise-vs-untiled gate runs inside every
    dry run), mesh_serving not yet.  (The live ledger_diff gate
    baseline moved to the r21 record below when the mesh-serving PR
    grew the family set.)"""
    _assert_cold_warm_record(
        os.path.join(_REPO, "artifacts", "ledger_dryrun_r20_4dev.jsonl"),
        FAMILIES_PRE_MESH)


def test_committed_r21_4dev_record_carries_mesh_serving():
    """The mesh-serving PR's committed 4-device record
    (artifacts/ledger_dryrun_r21_4dev.jsonl, the ledger_diff gate
    baseline r21 through the mesh-serving PR): cold+warm pair on its
    historical family set — mesh_serving included (the serving tick
    driven end to end through a Batcher whose megabatch shards over
    the whole dry-run mesh), request_trace not yet — warm run all-hit,
    steady and warm budgets held, >= 3x warm-start aggregate,
    provenance present.  (The live ledger_diff gate baseline moved to
    the r22 record below when the tracing PR grew the family set.)"""
    _assert_cold_warm_record(
        os.path.join(_REPO, "artifacts", "ledger_dryrun_r21_4dev.jsonl"),
        FAMILIES_PRE_TRACE)


def test_committed_r22_4dev_record_carries_request_trace():
    """The tracing PR's committed 4-device record
    (artifacts/ledger_dryrun_r22_4dev.jsonl, the ledger_diff gate
    baseline for r22): cold+warm pair on its historical family set —
    request_trace included (a live router+batcher pair driven through
    SidecarClient with minted trace ids, the cross-half waterfall join
    asserted inside the dry-run body) — warm run all-hit apart from
    the host-only request_trace family (cache="none": it compiles
    nothing of its own), steady and warm budgets held, >= 3x
    warm-start aggregate, provenance present."""
    _assert_cold_warm_record(
        os.path.join(_REPO, "artifacts", "ledger_dryrun_r22_4dev.jsonl"),
        FAMILIES_PRE_OVERLAP, host_only=frozenset({"request_trace"}))


def test_committed_r23_4dev_record_carries_stream_overlap():
    """The pipelined-streaming PR's committed 4-device record
    (artifacts/ledger_dryrun_r23_4dev.jsonl): cold+warm pair on its
    historical family set — scale_stream_overlap included (a forced
    >=3-tile pipelined run gated bitwise against the untiled reference
    inside the dry-run body, salted steady re-entry), cost_attribution
    not yet — warm run all-hit apart from the host-only request_trace
    family, steady and warm budgets held, >= 3x warm-start aggregate,
    provenance present.  (The live ledger_diff gate baseline moved to
    the r24 record below when the observability PR grew the family
    set.)"""
    _assert_cold_warm_record(
        os.path.join(_REPO, "artifacts", "ledger_dryrun_r23_4dev.jsonl"),
        FAMILIES_PRE_COST, host_only=frozenset({"request_trace"}))


def test_committed_r24_4dev_record_carries_cost_attribution():
    """The observability PR's committed 4-device record
    (artifacts/ledger_dryrun_r24_4dev.jsonl): cold+warm pair on its
    historical family set — cost_attribution included (a tiny probe
    acquired through the utils/compile_cache.load_or_compile
    chokepoint plus a salted fresh-closure re-entry), byzantine_conv
    not yet.  The family sits with request_trace outside the plain-jit
    all-hit proof: its compiles travel the AOT chokepoint, invisible
    to the persistent-cache monitor (warm ``compile`` event
    cache="none"); its warm-start proof is the chokepoint's OWN
    ``xla_compile`` hit verdicts, asserted below.  (The live
    ledger_diff gate baseline moved to the r25 record below when the
    byzantine-nemesis PR grew the family set.)"""
    path = os.path.join(_REPO, "artifacts",
                        "ledger_dryrun_r24_4dev.jsonl")
    _assert_cold_warm_record(
        path, FAMILIES_PRE_BYZ,
        host_only=frozenset({"request_trace", "cost_attribution"}))
    # the chokepoint's own attribution events carry the warm proof:
    # cold leg = (miss, hit) — forced first compile, salted re-entry
    # HIT in the same process; warm leg = (hit, hit) — the store
    # served the executable across processes
    all_events = telemetry.load_ledger(path)
    run_ids = telemetry_report.runs(all_events)
    per_run = []
    for rid in run_ids:
        per_run.append([e["cache"] for e in all_events
                        if e.get("run") == rid
                        and e.get("ev") == "xla_compile"
                        and e.get("label") == "cost_probe"])
    assert per_run == [["miss", "hit"], ["hit", "hit"]]


def test_committed_r25_4dev_record_carries_byzantine_conv():
    """The byzantine-nemesis PR's committed 4-device record
    (artifacts/ledger_dryrun_r25_4dev.jsonl, the ledger_diff gate
    baseline since r25): cold+warm pair, FULL current family set —
    byzantine_conv included (the DEFENDED sharded CRDT step under a
    MIXED nemesis: fail-stop churn + partition + ramp PLUS a scripted
    liar program, defenses on; the steady leg re-enters the SAME
    executable with a salted liar program — different liars, rounds,
    kinds and quorum — the pure-operand proof that byz content never
    enters the trace).  byzantine_conv is a plain-jit family, so it
    sits INSIDE the all-hit warm proof, unlike the two host-only
    chokepoint families.  Steady and warm budgets held, >= 3x
    warm-start aggregate, provenance present; the cost probe's
    chokepoint verdicts stay pinned as in r24."""
    path = os.path.join(_REPO, "artifacts",
                        "ledger_dryrun_r25_4dev.jsonl")
    _assert_cold_warm_record(
        path, FAMILIES,
        host_only=frozenset({"request_trace", "cost_attribution"}))
    all_events = telemetry.load_ledger(path)
    run_ids = telemetry_report.runs(all_events)
    per_run = []
    for rid in run_ids:
        per_run.append([e["cache"] for e in all_events
                        if e.get("run") == rid
                        and e.get("ev") == "xla_compile"
                        and e.get("label") == "cost_probe"])
    assert per_run == [["miss", "hit"], ["hit", "hit"]]


def test_committed_r09_4dev_record_matches_live_pair_shape(dryrun_pair):
    """The 4-device committed record exists for ledger_diff's
    like-for-like tier-1 gate (tests/test_ledger_diff.py): same family
    set and device count as the live dryrun_pair, warm run all-hit."""
    path = os.path.join(_REPO, "artifacts",
                        "ledger_dryrun_r09_4dev.jsonl")
    all_events = telemetry.load_ledger(path)
    run_ids = telemetry_report.runs(all_events)
    assert len(run_ids) == 2
    warm = [e for e in all_events if e.get("run") == run_ids[1]]
    assert any(e["ev"] == "runtime" and e["device_count"] == 4
               for e in warm)
    assert set(telemetry_report.family_table(warm)) == FAMILIES_PRE_CHURN
    assert all(e["cache"] == "hit" for e in warm
               if e.get("ev") == "compile"
               and e.get("phase") == "first_ms")
    live = telemetry.load_ledger(dryrun_pair["warm"]["ledger_path"],
                                 run="last")
    assert set(telemetry_report.family_table(live)) == FAMILIES
