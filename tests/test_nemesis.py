"""Compiled nemesis (ops/nemesis): schedule validation + lowering,
partition-heal acceptance on the dense AND sparse exchanges, churn
parity across mesh shapes, SWIM churn timelines, engine rejection
paths, the nemesis round-metrics observables, and the sidecar's
transport-retry contract.

The heal bounds asserted here are the docs/ROBUSTNESS.md ones:
coverage provably stalls at the cut while a window is open (the far
side starts clean and nothing crosses), then reaches target within
~2 epidemic legs + slack after heal; SWIM confirms a permanent crash
and never permanently confirms a node that recovers inside the
suggested suspicion timeout.
"""

import json
import os

import numpy as np
import pytest

from gossip_tpu import config as C
from gossip_tpu.config import (ChurnConfig, FaultConfig, ProtocolConfig,
                               RunConfig)
from gossip_tpu.topology import generators as G

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- config validation (satellite: FaultConfig probability guards) ----

def test_fault_config_rejects_out_of_range_probabilities():
    with pytest.raises(ValueError, match="node_death_rate"):
        FaultConfig(node_death_rate=1.5)
    with pytest.raises(ValueError, match="node_death_rate"):
        FaultConfig(node_death_rate=-0.1)
    with pytest.raises(ValueError, match="drop_prob"):
        FaultConfig(drop_prob=1.5)
    with pytest.raises(ValueError, match="drop_prob"):
        FaultConfig(drop_prob=-0.2)
    # the boundary values stay legal
    FaultConfig(node_death_rate=1.0, drop_prob=1.0)


def test_churn_config_validation():
    ChurnConfig(events=((3, 2, 5), (7, 1, -1)),
                partitions=((0, 4, 8), (6, 9, 16)),
                ramp=(0, 3, 0.0, 1.0))
    with pytest.raises(ValueError, match="recover_round"):
        ChurnConfig(events=((3, 5, 5),))          # rec must be > die
    with pytest.raises(ValueError, match="at most once"):
        ChurnConfig(events=((3, 1, 2), (3, 5, -1)))
    with pytest.raises(ValueError, match="die_round"):
        ChurnConfig(events=((3, -1, 2),))
    with pytest.raises(ValueError, match="overlap"):
        ChurnConfig(partitions=((0, 5, 8), (4, 9, 16)))
    with pytest.raises(ValueError, match="cut"):
        ChurnConfig(partitions=((0, 5, 0),))
    with pytest.raises(ValueError, match="start < end"):
        ChurnConfig(partitions=((5, 5, 8),))
    with pytest.raises(ValueError, match="outside"):
        ChurnConfig(ramp=(0, 3, 0.0, 1.5))
    with pytest.raises(ValueError, match="start < end"):
        ChurnConfig(ramp=(3, 3, 0.0, 0.5))
    # the horizon cap: an absurd end would materialize a giant [T]
    # table (and host list) per trace — reject at config time
    with pytest.raises(ValueError, match="horizon cap"):
        ChurnConfig(partitions=((0, 1_000_000_000, 8),))
    with pytest.raises(ValueError, match="horizon cap"):
        ChurnConfig(ramp=(0, 1_000_000_000, 0.0, 0.5))
    # the cap itself stays legal
    from gossip_tpu.config import MAX_CHURN_HORIZON
    ChurnConfig(partitions=((0, MAX_CHURN_HORIZON, 8),))
    # wrong-arity ramp: the clean ValueError every other malformed
    # churn field gets, not a raw IndexError from the coercion
    with pytest.raises(ValueError, match="start, end, from_p, to_p"):
        ChurnConfig(ramp=(0, 5))
    # event rounds are capped too: a die/rec at ~2**29 would collide
    # with the kernels' int32 NEVER sentinel (a rec >= NEVER would read
    # as 'permanent' to the fused denominator but 'recovers' to
    # eventual_alive) — rec < 0 is the one way to say forever
    with pytest.raises(ValueError, match="horizon cap"):
        ChurnConfig(events=((5, 0, 1 << 29),))
    with pytest.raises(ValueError, match="horizon cap"):
        ChurnConfig(events=((5, 1 << 31, -1),))


def test_vacuous_churn_normalizes_to_none_and_rpc_dict_coerces():
    # an all-default schedule keeps the static hot path (and its pins)
    assert FaultConfig(drop_prob=0.1, churn=ChurnConfig()).churn is None
    # the RPC fault object delivers churn as a nested JSON dict
    f = FaultConfig(drop_prob=0.1, churn={
        "events": [[3, 2, 5]], "partitions": [[0, 4, 8]],
        "ramp": [1, 3, 0.0, 0.5]})
    assert isinstance(f.churn, ChurnConfig)
    assert f.churn.events == ((3, 2, 5),)
    assert f.churn.ramp == (1, 3, 0.0, 0.5)
    # horizon: the round after which the schedule is constant
    assert ChurnConfig(partitions=((0, 6, 8),)).horizon() == 7
    assert ChurnConfig(events=((1, 2, 4),)).horizon() == 2


def test_schedule_lowering_tables():
    from gossip_tpu.ops import nemesis as NE
    f = FaultConfig(drop_prob=0.1, seed=0, churn=ChurnConfig(
        events=((3, 2, 5), (7, 1, -1)),
        partitions=((2, 4, 8),), ramp=(1, 3, 0.0, 0.4)))
    s = NE.build(f, 16)
    assert int(s.die[3]) == 2 and int(s.rec[3]) == 5
    assert int(s.die[7]) == 1 and int(s.rec[7]) == NE.NEVER
    # cut table: open exactly for [2, 4), clamped lookup exact after T
    for r, want in ((0, -1), (2, 8), (3, 8), (4, -1), (100, -1)):
        assert int(NE.cut_at(s, r)) == want, r
    # drop ramp: base before start, linear inside, held after (exactly
    # — the clamped last row IS the steady state)
    assert float(NE.drop_at(s, 0)) == pytest.approx(0.1)
    assert float(NE.drop_at(s, 2)) == pytest.approx(0.2)
    assert float(NE.drop_at(s, 3)) == pytest.approx(0.4)
    assert float(NE.drop_at(s, 1000)) == pytest.approx(0.4)
    # per-round liveness: down during [die, rec)
    import jax.numpy as jnp
    base = jnp.ones((16,), bool)
    for r, alive3, alive7 in ((1, True, False), (2, False, False),
                              (4, False, False), (5, True, False)):
        a = NE.alive_rows(s, base, r)
        assert bool(a[3]) == alive3 and bool(a[7]) == alive7, r
    # out-of-range scripted ids are a loud error, not a silent no-op
    with pytest.raises(ValueError, match="node ids"):
        NE.validate_events(FaultConfig(churn=ChurnConfig(
            events=((99, 0, -1),))), 16)


# -- partition-heal acceptance (dense + sparse, the ISSUE gate) -------

_HEAL_N = 64
_HEAL_END = 6


def _heal_bound(fanout):
    # ~2 epidemic legs + slack after the window closes (ROBUSTNESS.md)
    import math
    leg = math.ceil(math.log(_HEAL_N) / math.log(1 + fanout))
    return _HEAL_END + 2 * leg + 4


def test_partition_heal_dense():
    """Coverage provably stalls across the open cut (the far side
    starts clean, push cannot cross), then converges to target within
    the documented bound after heal."""
    from gossip_tpu.runtime.simulator import simulate_curve
    topo = G.complete(_HEAL_N)
    proto = ProtocolConfig(mode=C.PUSH, fanout=2, rumors=1)
    fault = FaultConfig(seed=0, churn=ChurnConfig(
        partitions=((0, _HEAL_END, 48),)))
    run = RunConfig(seed=0, max_rounds=24, target_coverage=1.0)
    res = simulate_curve(proto, topo, run, fault)
    # stalled: nothing reaches ids >= 48 while the window is open
    assert all(c <= 48 / _HEAL_N + 1e-6
               for c in res.coverage[:_HEAL_END]), res.coverage
    # healed: full coverage within the bound
    assert res.rounds_to_target != -1
    assert res.rounds_to_target <= _heal_bound(2), (
        res.rounds_to_target, list(res.coverage))
    # and the no-churn control crosses the "cut" early — the stall was
    # the schedule, not the protocol
    free = simulate_curve(proto, topo, run, None)
    assert any(c > 48 / _HEAL_N for c in free.coverage[:_HEAL_END])


def test_partition_heal_sparse():
    """The same stall/heal invariant on the sparse all_to_all exchange
    (complete-graph stratified pull), mesh-sharded."""
    from gossip_tpu.parallel.sharded import make_mesh
    from gossip_tpu.parallel.sharded_sparse import simulate_curve_sparse
    mesh = make_mesh(4)
    proto = ProtocolConfig(mode=C.PULL, fanout=1, rumors=1)
    fault = FaultConfig(seed=0, churn=ChurnConfig(
        partitions=((0, _HEAL_END, 32),)))
    run = RunConfig(seed=0, max_rounds=24, target_coverage=1.0)
    covs, msgs, fin, meta = simulate_curve_sparse(proto, _HEAL_N, run,
                                                  mesh, fault)
    assert all(c <= 32 / _HEAL_N + 1e-6 for c in covs[:_HEAL_END]), covs
    hit = np.nonzero(np.asarray(covs) >= 1.0)[0]
    assert len(hit), f"sparse never healed: {list(covs)}"
    assert int(hit[0]) + 1 <= _heal_bound(1) + 6, list(covs)


# -- churn parity across mesh shapes ----------------------------------

_CHURN = ChurnConfig(events=((3, 2, 5), (7, 1, -1)),
                     partitions=((2, 6, 32),), ramp=(1, 4, 0.0, 0.3))
_CFAULT = FaultConfig(node_death_rate=0.1, drop_prob=0.05, seed=1,
                      churn=_CHURN)


# depth tier (tier-1 wall budget, PR 7 rebalance): churn mesh-
# invariance stays in-gate via the traced-operand fingerprint subset
# (sharded churn surfaces); this exhaustive twin runs under -m slow
@pytest.mark.slow
def test_churn_parity_single_vs_sharded_dense():
    """The full schedule (events + window + ramp) stacked on static
    faults: bitwise-identical trajectory at 1 and 4 devices — the
    cross-mesh twin of the static bitwise-parity pins (drop coins and
    peer draws are keyed by GLOBAL node id; the schedule tables are
    mesh-shape free)."""
    from gossip_tpu.parallel.sharded import make_mesh, \
        simulate_curve_sharded
    from gossip_tpu.runtime.simulator import simulate_curve
    topo = G.complete(64)
    proto = ProtocolConfig(mode=C.PUSH_PULL, fanout=2, rumors=2)
    run = RunConfig(seed=0, max_rounds=12)
    res = simulate_curve(proto, topo, run, _CFAULT)
    covs, msgs, fin = simulate_curve_sharded(proto, topo, run,
                                             make_mesh(4), _CFAULT)
    assert np.array_equal(np.asarray(res.coverage), np.asarray(covs))
    assert np.array_equal(np.asarray(res.msgs), np.asarray(msgs))
    assert np.array_equal(np.asarray(res.state.seen),
                          np.asarray(fin.seen)[:64])


# ~6 s (txn-PR rebalance): the sparse exchange keeps its in-gate
# churn smoke via the dry run's sparse families and the dense/packed
# churn parities pin the schedule-operand mechanism; the
# mesh-vs-reference depth re-proves under -m slow
@pytest.mark.slow
def test_sparse_mesh_vs_reference_churn_parity():
    import jax
    from gossip_tpu.parallel.sharded import make_mesh
    from gossip_tpu.parallel.sharded_sparse import (
        init_sparse_state, make_sparse_pull_round,
        sparse_pull_round_reference)
    n = 64
    proto = ProtocolConfig(mode=C.ANTI_ENTROPY, fanout=2, rumors=3,
                           period=2)
    run = RunConfig(seed=0, max_rounds=6)
    sm = init_sparse_state(run, proto, n, make_mesh(4))
    sr = init_sparse_state(run, proto, n, p=4)
    jm = jax.jit(make_sparse_pull_round(proto, n, make_mesh(4),
                                        _CFAULT, 0))
    jr = jax.jit(sparse_pull_round_reference(proto, n, 4, _CFAULT, 0))
    for r in range(4):
        sm, lm = jm(sm)
        sr, lr = jr(sr)
        assert np.array_equal(np.asarray(sm.seen), np.asarray(sr.seen))
        assert float(lm) == float(lr), r


def test_packed_matches_unpacked_bitwise_under_churn():
    import jax
    from gossip_tpu.models.si import make_si_round
    from gossip_tpu.models.si_packed import (init_packed_state,
                                             make_packed_round)
    from gossip_tpu.models.state import init_state
    from gossip_tpu.ops.bitpack import unpack
    n = 64
    topo = G.complete(n)
    proto = ProtocolConfig(mode=C.PULL, fanout=2, rumors=3)
    run = RunConfig(seed=0, max_rounds=6)
    sp = init_packed_state(run, proto, n)
    su = init_state(run, proto, n)
    stp = jax.jit(make_packed_round(proto, topo, _CFAULT, 0))
    stu = jax.jit(make_si_round(proto, topo, _CFAULT, 0))
    for r in range(4):
        sp, lp = stp(sp)
        su, lu = stu(su)
        assert np.array_equal(np.asarray(unpack(sp.seen, proto.rumors)),
                              np.asarray(su.seen)), r
        assert float(lp) == float(lu), r


def test_fault_mask_cross_mesh_determinism():
    """The same FaultConfig draw kills the same node ids at 1 and 4
    devices — sharded_alive's real rows ARE the single-device mask,
    including when padding rows exist (n not divisible)."""
    from gossip_tpu.models.state import alive_mask
    from gossip_tpu.parallel.sharded import make_mesh, pad_to_mesh, \
        sharded_alive
    n = 61                                       # pads to 64 on 4 dev
    fault = FaultConfig(node_death_rate=0.3, seed=7)
    mesh = make_mesh(4)
    n_pad = pad_to_mesh(n, mesh, "nodes")
    assert n_pad == 64
    single = np.asarray(alive_mask(fault, n, 0))
    padded = np.asarray(sharded_alive(fault, n, n_pad, 0))
    assert np.array_equal(single, padded[:n])
    assert not padded[n:].any()                  # padding rows dead
    # and the draw is seed-deterministic: same ids on a re-draw
    assert np.array_equal(single, np.asarray(alive_mask(fault, n, 0)))
    dead_ids = np.nonzero(~single)[0]
    assert len(dead_ids) > 0                     # 0.3 of 61 draws some


# -- seed ensembles under churn (sweep.py) ----------------------------

# depth tier (tier-1 wall budget, PR 7 rebalance): base ensemble-vs-
# solo parity stays in-gate (tests/test_sweep.py); the churn-schedule
# ensemble twin runs under -m slow
@pytest.mark.slow
def test_ensemble_churn_matches_solo_curves():
    """ensemble_curves under the full schedule: each seed's batched
    trajectory equals the solo simulate_curve run — the drop_lost
    wrapper discards the lost count without touching the state, and
    the coverage denominator is the same eventual alive set."""
    from gossip_tpu.parallel.sweep import ensemble_curves
    from gossip_tpu.runtime.simulator import simulate_curve
    topo = G.complete(64)
    proto = ProtocolConfig(mode=C.PUSH_PULL, fanout=2, rumors=2)
    seeds = [0, 3]
    ens = ensemble_curves(proto, topo, RunConfig(max_rounds=10), seeds,
                          _CFAULT)
    for i, seed in enumerate(seeds):
        solo = simulate_curve(proto, topo,
                              RunConfig(max_rounds=10, seed=seed),
                              _CFAULT)
        np.testing.assert_array_equal(ens.curves[i],
                                      np.asarray(solo.coverage))
        np.testing.assert_array_equal(ens.msgs[i],
                                      np.asarray(solo.msgs))


def test_ensemble_rumor_churn_matches_solo():
    """The rumor ensemble's churn twin: bitwise per-seed parity with
    simulate_curve_rumor (same metric_alive denominator and hot
    weighting)."""
    from gossip_tpu.models.rumor import simulate_curve_rumor
    from gossip_tpu.parallel.sweep import ensemble_rumor_curves
    proto = ProtocolConfig(mode="rumor", fanout=1, rumor_k=2)
    topo = G.complete(64)
    fault = FaultConfig(seed=1, churn=ChurnConfig(
        events=((3, 2, 5), (7, 1, -1)), partitions=((2, 5, 32),)))
    run = RunConfig(max_rounds=24, seed=3)
    ens = ensemble_rumor_curves(proto, topo, run, [3, 4], fault)
    solo_covs, solo_hots, solo_msgs, _ = simulate_curve_rumor(
        proto, topo, RunConfig(max_rounds=24, seed=4), fault)
    np.testing.assert_array_equal(ens.curves[1], np.asarray(solo_covs))
    np.testing.assert_array_equal(ens.hot[1], np.asarray(solo_hots))
    np.testing.assert_array_equal(ens.msgs[1], np.asarray(solo_msgs))


def test_ensemble_swim_churn_observer_denominator():
    """ensemble_swim_curves excludes PERMANENT churn deaths from the
    observer denominator (matching simulate_swim_curve): detection of
    a scripted crash reaches 1.0 even though the churn-dead node can
    never confirm it."""
    from gossip_tpu.models import swim as SW
    from gossip_tpu.parallel.sweep import ensemble_swim_curves
    n = 64
    t = SW.suggested_suspect_rounds(n, 2)
    proto = ProtocolConfig(mode=C.SWIM, fanout=2, swim_subjects=8,
                           swim_proxies=2, swim_suspect_rounds=t)
    fault = FaultConfig(seed=1, churn=ChurnConfig(events=((5, 2, -1),)))
    ens = ensemble_swim_curves(
        proto, n, RunConfig(max_rounds=36, target_coverage=1.0),
        seeds=[0, 1], dead_nodes=(1,), fail_round=0, fault=fault)
    assert (ens.curves[:, -1] == 1.0).all()
    assert (ens.rounds_to_target >= 0).all()


def test_config_sweep_rejects_churn():
    """The grid sweeps have no churn lowering — a schedule must reject
    loudly, never run static-only (the no-silent-substitution policy)."""
    from gossip_tpu.parallel.sweep import SweepPoint, config_sweep_curves
    with pytest.raises(ValueError, match="churn"):
        config_sweep_curves((SweepPoint(mode=C.PUSH, fanout=1),),
                            G.complete(64), RunConfig(max_rounds=4),
                            fault=FaultConfig(seed=1, churn=ChurnConfig(
                                events=((3, 1, -1),))))


# -- engine rejection paths (no silent substitution) ------------------

def test_unsupported_engines_reject_loudly():
    from gossip_tpu.parallel.sharded import make_mesh
    mesh = make_mesh(4)
    part = FaultConfig(seed=0, churn=ChurnConfig(
        partitions=((0, 4, 32),)))
    ramp = FaultConfig(seed=0, churn=ChurnConfig(ramp=(0, 2, 0.0, 0.5)))
    ev = FaultConfig(seed=0, churn=ChurnConfig(events=((1, 0, -1),)))
    # topo-sparse: no churn at all
    from gossip_tpu.parallel.sharded_sparse import \
        make_sparse_topo_pull_round
    with pytest.raises(ValueError, match="churn"):
        make_sparse_topo_pull_round(
            ProtocolConfig(mode=C.PULL, fanout=1, rumors=1),
            G.erdos_renyi(64, 0.2, seed=0), mesh, ev)
    # swim: events + drop ramps (the schedule rides as operands since
    # the traced-operand PR — the old "bakes its drop threshold"
    # rejection is gone); partitions stay impossible (probes ride the
    # complete membership overlay)
    from gossip_tpu.models.swim import make_swim_round
    wproto = ProtocolConfig(mode=C.SWIM, fanout=2, swim_subjects=4,
                            swim_proxies=2, swim_suspect_rounds=3)
    with pytest.raises(ValueError, match="partition"):
        make_swim_round(wproto, 64, fault=part)
    make_swim_round(wproto, 64, fault=ramp)       # accepted now
    # fused planes run the FULL schedule since the fused-operand PR:
    # partition windows lower to per-round side-word cut masks and
    # drop-rate ramps index the 20-bit threshold table behind the SMEM
    # scalar — the two rejection rows are DELETED, not special-cased
    # (tests/test_sharded_fused.py pins the semantics; here the driver
    # entries must simply accept what they used to refuse)
    from gossip_tpu.parallel.sharded_fused import (
        make_plane_mesh, simulate_until_sharded_fused)
    for fch in (part, ramp):
        rounds_f, _, _, _ = simulate_until_sharded_fused(
            128 * 8, 40, RunConfig(seed=0, max_rounds=2),
            make_plane_mesh(4), interpret=True, fault=fch)
        assert rounds_f == 2
    # checkpointed drivers came OFF the rejection list (crash-safety
    # PR): churn runs in the segments with bitwise resume
    # (tests/test_crash_safety.py pins every surface); only the engines
    # above remain on events=False
    # the fused ENGINE routing still sends churn back to the XLA
    # kernels single-device (those paths predate the churn
    # denominator) — the plane-stack route (checkpointed CLI,
    # churn-sweep --engine fused) accepts the full schedule: events,
    # partitions, AND ramps
    from gossip_tpu.backend import _fused_ineligible_reason
    from gossip_tpu.config import TopologyConfig
    fproto = ProtocolConfig(mode=C.PULL, fanout=1, rumors=1)
    ftc = TopologyConfig(family="complete", n=64)
    reason = _fused_ineligible_reason(fproto, ftc, ev, 1)
    assert reason and "churn" in reason
    # every schedule class passes the plane-stack churn gate: any
    # remaining reason is a later precondition (on CPU, the platform
    # probe), never churn/partition/ramp
    for fch in (ev, part, ramp):
        reason = _fused_ineligible_reason(fproto, ftc, fch, 1,
                                          plane_stack=True)
        assert reason is None or "TPU" in reason


# -- SWIM churn timeline ----------------------------------------------

def test_swim_churn_confirms_crash_never_recovered_node():
    """The heal gate for failure detection: a permanent churn crash is
    confirmed DEAD by every alive observer; a node that recovers
    within the suggested suspicion timeout refutes and is NEVER
    permanently confirmed.  Sharded twin bitwise-identical."""
    from gossip_tpu.models import swim as SW
    from gossip_tpu.parallel.sharded import make_mesh
    from gossip_tpu.runtime.simulator import simulate_swim_curve
    n, rounds = 64, 36
    t = SW.suggested_suspect_rounds(n, 2)
    proto = ProtocolConfig(mode=C.SWIM, fanout=2, swim_subjects=8,
                           swim_proxies=2, swim_suspect_rounds=t)
    fault = FaultConfig(seed=1, churn=ChurnConfig(
        events=((5, 2, -1), (3, 4, 6))))
    fr, fin = simulate_swim_curve(proto, n, rounds, dead_nodes=(),
                                  fail_round=0, fault=fault)
    status = np.asarray(SW.decode_status(fin.wire))
    obs = np.asarray(SW.observer_alive(n, (), fault))
    assert not obs[5]                 # permanent churn death observes not
    assert (status[obs, 5] == SW.DEAD).all(), "true crash not confirmed"
    assert (status[obs, 3] != SW.DEAD).all(), \
        "recovered node permanently confirmed"
    # sharded twin: bitwise wire parity under churn
    fr2, fin2 = simulate_swim_curve(proto, n, rounds, dead_nodes=(),
                                    fail_round=0, fault=fault,
                                    mesh=make_mesh(4))
    assert np.array_equal(np.asarray(fin.wire),
                          np.asarray(fin2.wire)[:n])


# depth tier (tier-1 wall budget, PR 7 rebalance): the churn-only SWIM
# scenario keeps in-gate coverage via test_swim_honors_drop_ramp and
# the crash-safety pin (detection 1.0 on a scheduled crash across a
# kill); the full scenario-semantics check runs under -m slow
@pytest.mark.slow
def test_swim_churn_only_scenario_targets_churn_deaths():
    """A churn-only SWIM run is a SCRIPTED scenario: no default static
    death is injected on top of the schedule, the detection metric
    targets the permanent churn crashes (models/swim.detection_targets
    wires nemesis.permanent_dead_ids in), and the run converges to
    detection 1.0 on them."""
    from gossip_tpu import backend
    from gossip_tpu.models import swim as SW
    from gossip_tpu.runtime.simulator import (simulate_swim_curve,
                                              simulate_swim_until)
    n = 64
    t = SW.suggested_suspect_rounds(n, 2)
    proto = ProtocolConfig(mode=C.SWIM, fanout=2, swim_subjects=8,
                           swim_proxies=2, swim_suspect_rounds=t)
    fault = FaultConfig(seed=1, churn=ChurnConfig(events=((5, 2, -1),)))
    dead, fail_round, meta = backend.swim_scenario_meta(proto, n, fault)
    assert dead == ()                    # nothing statically scripted
    assert meta["default_scenario"] is False
    assert meta["dead_subjects"] == [5]  # the metric's real target set
    fr, _ = simulate_swim_curve(proto, n, 30, dead_nodes=dead,
                                fail_round=fail_round, fault=fault)
    assert fr[-1] == 1.0                 # the churn crash IS detected
    rounds, det, _, _ = simulate_swim_until(proto, n, 40, 1.0,
                                            dead_nodes=dead,
                                            fail_round=fail_round,
                                            fault=fault)
    assert det == 1.0 and rounds < 40
    # recover-only churn: still scripted (no default injection), but no
    # permanent deaths -> no targets, detection stays 0 (refutation)
    fault2 = FaultConfig(seed=1, churn=ChurnConfig(events=((5, 2, 4),)))
    dead2, fr2_, meta2 = backend.swim_scenario_meta(proto, n, fault2)
    assert dead2 == () and meta2["dead_subjects"] == []
    fr2, _ = simulate_swim_curve(proto, n, 20, dead_nodes=dead2,
                                 fail_round=fr2_, fault=fault2)
    assert fr2[-1] == 0.0


def test_fused_rejects_out_of_range_churn_event():
    """The fused word tables validate event ids like every other engine
    — an id >= n would land on a phantom lane and silently kill nobody
    (the no-silent-substitution policy)."""
    from gossip_tpu.ops import nemesis as NE
    bad = FaultConfig(seed=1, churn=ChurnConfig(events=((70, 2, -1),)))
    with pytest.raises(ValueError, match="node ids >= n"):
        NE.fused_word_tables(bad, 64)
    with pytest.raises(ValueError, match="node ids >= n"):
        NE.build(bad, 64)


# -- nemesis observables in the round-metrics plane -------------------

def test_round_metrics_carry_nemesis_observables(tmp_path):
    from gossip_tpu.parallel.sharded import make_mesh, \
        simulate_curve_sharded
    from gossip_tpu.utils import telemetry
    path = str(tmp_path / "churn.jsonl")
    proto = ProtocolConfig(mode=C.PUSH_PULL, fanout=2, rumors=2)
    fault = FaultConfig(drop_prob=0.05, seed=1, churn=ChurnConfig(
        events=((3, 2, 5),), partitions=((0, 4, 32),)))
    run = RunConfig(seed=0, max_rounds=8)
    led = telemetry.Ledger(path)
    prev = telemetry.activate(led)
    try:
        simulate_curve_sharded(proto, G.complete(64), run, make_mesh(4),
                               fault)
    finally:
        telemetry.activate(prev)
        led.close()
    evs = telemetry.load_ledger(path)
    rms = [e for e in evs if e.get("ev") == "round_metrics"]
    assert rms, "no round_metrics event ledgered"
    e = rms[-1]
    assert e["rounds"] == 8
    for series in ("alive", "cut_pairs", "dropped"):
        assert len(e[series]) == 8, series
    # the window [0, 4) separates alive pairs; closed after
    assert all(p > 0 for p in e["cut_pairs"][:4])
    assert all(p == 0 for p in e["cut_pairs"][4:])
    # node 3 down during rounds [2, 5): alive count dips by exactly 1
    assert e["alive"][0] == 64 and e["alive"][2] == 63
    assert e["alive"][5] == 64
    # dropped totals join the gated totals and match the series
    assert e["totals"]["dropped"] == pytest.approx(
        sum(e["dropped"]), abs=0.01)
    # the report renders the dropped column from this ledger
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "ledger_diff", os.path.join(_REPO, "tools", "ledger_diff.py"))
    ledger_diff = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ledger_diff)
    md = "\n".join(ledger_diff.render_protocol_metrics(evs))
    assert "dropped" in md and "simulate_curve_sharded" in md


def test_committed_churn_artifact_renders():
    """The committed churn-scenario record
    (artifacts/ledger_churn_r10.jsonl): provenance-carrying, nemesis
    totals present on BOTH exchanges (dense + sparse), heal reached."""
    from gossip_tpu.utils import telemetry
    path = os.path.join(_REPO, "artifacts", "ledger_churn_r10.jsonl")
    evs = telemetry.load_ledger(path, run="last")
    assert evs[0]["ev"] == "provenance"
    assert len(evs[0]["git_commit"]) == 40
    rms = {e["driver"]: e for e in evs
           if e.get("ev") == "round_metrics"}
    assert {"simulate_curve_sharded", "simulate_curve_sparse"} \
        <= set(rms)
    for e in rms.values():
        assert e["totals"]["dropped"] > 0
        assert any(p > 0 for p in e["cut_pairs"])
    curves = {e["family"]: e for e in evs
              if e.get("ev") == "churn_curve"}
    assert curves["dense_pushpull"]["final"] == 1.0
    assert curves["sparse_pull"]["final"] == 1.0


def test_validate_artifacts_requires_provenance_on_nemesis(tmp_path):
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "validate_artifacts",
        os.path.join(_REPO, "tools", "validate_artifacts.py"))
    va = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(va)
    bad = tmp_path / "churn_scenario_rXX.jsonl"
    bad.write_text(json.dumps({"ev": "round_metrics_free_rider"}) + "\n")
    problems = va.validate_file(str(bad))
    assert problems and any("nemesis" in p or "churn" in p
                            for p in problems)
    badj = tmp_path / "nemesis_sweep.json"
    badj.write_text(json.dumps({"coverage": [1.0]}))
    assert va.validate_file(str(badj))


# -- traced-operand schedule contract (the one-executable PR) ---------

def test_schedule_canonical_padding_is_exact():
    """The [T] tables pad to a power-of-two bucket by repeating the
    final row — the steady state by construction — so the clamped
    lookup is EXACT at every length and every padding choice, which is
    what lets memo keys (and the HLO fingerprint) carry only the
    bucket, never the content."""
    from gossip_tpu.ops import nemesis as NE
    ch = ChurnConfig(partitions=((2, 4, 8),), ramp=(1, 3, 0.0, 0.4))
    f = FaultConfig(drop_prob=0.1, churn=ch)
    assert NE.canonical_horizon(ch) == 32          # horizon 5 -> bucket
    long = ChurnConfig(partitions=((0, 40, 8),))
    assert NE.canonical_horizon(long) == 64
    s32 = NE.build(f, 16)
    s128 = NE.build(f, 16, t_pad=128)
    assert s32.cut_tbl.shape == (32,) and s128.cut_tbl.shape == (128,)
    for r in (0, 2, 3, 4, 31, 500):
        assert int(NE.cut_at(s32, r)) == int(NE.cut_at(s128, r)), r
        assert float(NE.drop_at(s32, r)) == float(NE.drop_at(s128, r)), r
    with pytest.raises(ValueError, match="below the schedule horizon"):
        NE.build(f, 16, t_pad=3)
    # the stack aligns mixed horizons to one bucket and keeps content
    st = NE.build_stack([f, FaultConfig(churn=long)], 16)
    assert st.cut_tbl.shape == (2, 64)
    assert int(st.cut_tbl[0, 2]) == 8 and int(st.cut_tbl[1, 20]) == 8
    # split_tables is the exact inverse of the sched_args layout
    tbl, sched = NE.split_tables(ch, ("nbrs", "deg")
                                 + NE.sched_args(s32))
    assert tbl == ("nbrs", "deg")
    assert sched.cut_tbl.shape == (32,)
    # a static-only stack entry rejects loudly
    with pytest.raises(ValueError, match="no churn"):
        NE.build_stack([f, FaultConfig(drop_prob=0.5)], 16)


def _fingerprint_surfaces(names):
    import json
    import sys
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    try:
        import _churn_surfaces as CS
    finally:
        sys.path.pop(0)
    with open(CS.DATA) as f:
        golden = json.load(f)["digests"]
    for name in names:
        runner, fault_of = CS.SURFACES[name]
        assert runner(fault_of()) == golden[f"churn:{name}"], (
            f"churn:{name} trajectory diverged from the PR 5 "
            "baked-schedule capture (tests/data/"
            "churn_fingerprints_r06.json)")
        if name in CS.NO_CHURN:
            assert runner(CS._static_fault()) == golden[
                f"static:{name}"], f"static:{name} moved"


def test_traced_operand_trajectories_match_pr5_bake():
    """Schedules as runtime operands must be a pure re-plumbing: the
    churn trajectories (and the static-fault hot path) on the core
    surfaces are BITWISE the golden digests captured from the PR 5
    baked-schedule tree.  The full 12-surface matrix runs in the slow
    tier; the in-gate digest is dense_sharded — the one surface that
    exercises ALL the new plumbing at once (host-side build, table-tail
    operands through shard_map replicated specs, the shape-keyed
    memoized loop, the eventual-alive operand) — because the other
    surfaces are already pinned in-gate against IT and each other by
    the cross-surface churn parity tests above (tier-1 wall budget:
    every extra surface here costs a compile)."""
    _fingerprint_surfaces(["dense_sharded"])


@pytest.mark.slow
def test_traced_operand_trajectories_full_matrix():
    """Every converted surface vs the PR 5 golden digests (in-gate
    subset above; rationale in tests/_churn_surfaces.py)."""
    import sys
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    try:
        import _churn_surfaces as CS
    finally:
        sys.path.pop(0)
    _fingerprint_surfaces(sorted(CS.SURFACES))


def test_dense_sharded_k_scenarios_compile_once(assert_compiles):
    """THE amortization acceptance: K=8 mixed nemesis scenarios
    (churn events, partition windows, drop ramps) through the dense
    sharded driver compile EXACTLY once — the shape-keyed memoized
    loop (_cached_dense_loop) takes schedule content and the
    eventual-alive denominator as operands, so scenarios 2..8 are pure
    in-memory executable reuses (zero backend compiles, pinned via the
    JitCompileMonitor fixture)."""
    from gossip_tpu.parallel import sharded
    topo = G.complete(64)
    proto = ProtocolConfig(mode=C.PUSH_PULL, fanout=2, rumors=2)
    run = RunConfig(seed=0, max_rounds=4)
    mesh = sharded.make_mesh(4)
    scens = [
        ChurnConfig(events=((3, 1, 3),)),
        ChurnConfig(events=((5, 2, -1),)),
        ChurnConfig(partitions=((0, 3, 32),)),
        ChurnConfig(partitions=((1, 3, 16),)),
        ChurnConfig(ramp=(0, 3, 0.0, 0.2)),
        ChurnConfig(ramp=(1, 3, 0.1, 0.4)),
        ChurnConfig(events=((7, 1, -1),), partitions=((0, 2, 48),)),
        ChurnConfig(events=((9, 1, 2),), ramp=(0, 2, 0.0, 0.1)),
    ]
    faults = [FaultConfig(drop_prob=0.05, seed=2, churn=ch)
              for ch in scens]
    sharded._cached_dense_loop.cache_clear()
    covs0, _, _ = sharded.simulate_curve_sharded(
        proto, topo, run, mesh, faults[0])       # the only compile
    with assert_compiles(0):
        for f in faults[1:]:
            covs, _, _ = sharded.simulate_curve_sharded(
                proto, topo, run, mesh, f)
            assert covs.shape == (4,)


# depth tier (tier-1 wall budget, PR 7 rebalance): churn_sweep keeps
# in-gate coverage via the dry-run churn_sweep family budgets + the
# compile-count pin; the K-scenario bitwise solo-parity sweep runs
# under -m slow
@pytest.mark.slow
def test_churn_sweep_matches_solo_bitwise():
    """Scenario-batched sweep (sweep.churn_sweep_curves): each
    scenario's curve/msgs equal the solo simulate_curve run BITWISE
    (same threefry keys; integer-exact coverage readout), mixed
    events + windows + ramps in one vmapped program."""
    from gossip_tpu.parallel.sweep import churn_sweep_curves
    from gossip_tpu.runtime.simulator import simulate_curve
    topo = G.complete(64)
    proto = ProtocolConfig(mode=C.PUSH_PULL, fanout=2, rumors=2)
    run = RunConfig(seed=0, max_rounds=10)
    faults = [
        FaultConfig(node_death_rate=0.1, seed=1, drop_prob=0.1,
                    churn=ChurnConfig(partitions=((1, 5, 32),),
                                      ramp=(0, 4, 0.0, 0.3))),
        FaultConfig(node_death_rate=0.1, seed=1,
                    churn=ChurnConfig(events=((7, 1, -1),),
                                      partitions=((2, 6, 16),))),
    ]
    res = churn_sweep_curves(proto, topo, run, faults)
    for i, f in enumerate(faults):
        solo = simulate_curve(proto, topo, run, f)
        np.testing.assert_array_equal(res.curves[i],
                                      np.asarray(solo.coverage))
        np.testing.assert_array_equal(res.msgs[i],
                                      np.asarray(solo.msgs))
    # mixed static structure rejects loudly (the step bakes the mask)
    with pytest.raises(ValueError, match="STATIC fault structure"):
        churn_sweep_curves(proto, topo, run, faults + [
            FaultConfig(node_death_rate=0.3, seed=1,
                        churn=ChurnConfig(events=((3, 1, 2),)))])


def test_churn_sweep_new_family_costs_no_compile(assert_compiles):
    """A SECOND scenario family of the same shapes re-enters the
    memoized vmapped scan with new schedule operands: zero backend
    compiles (the one-executable-every-scenario contract)."""
    from gossip_tpu.parallel.sweep import (_cached_churn_sweep_scan,
                                           churn_sweep_curves)
    topo = G.complete(64)
    proto = ProtocolConfig(mode=C.PUSH_PULL, fanout=2, rumors=2)
    run = RunConfig(seed=0, max_rounds=4)

    def family(salt, drop=0.0):
        return [FaultConfig(seed=1, drop_prob=drop, churn=ChurnConfig(
            events=(((3 * i + salt) % 64, 1, 3),))) for i in range(8)]

    _cached_churn_sweep_scan.cache_clear()
    churn_sweep_curves(proto, topo, run, family(0))   # the one compile
    with assert_compiles(0):
        res = churn_sweep_curves(proto, topo, run, family(7))
        # drop_prob only feeds the drop_tbl OPERAND — a family
        # differing in the base rate shares the loop too
        churn_sweep_curves(proto, topo, run, family(7, drop=0.1))
    assert res.curves.shape == (8, 4)


def test_swim_honors_drop_ramp():
    """The rejection list shrank: SWIM consumes drop_tbl[r] as a
    traced operand, so a drop-rate ramp is a legal SWIM schedule.  A
    ramp to heavy loss slows/pauses detection while it holds, the
    permanent crash is still confirmed, and the sharded twin stays
    bitwise identical."""
    from gossip_tpu.models import swim as SW
    from gossip_tpu.parallel.sharded import make_mesh
    from gossip_tpu.runtime.simulator import simulate_swim_curve
    n, rounds = 64, 36
    t = SW.suggested_suspect_rounds(n, 2)
    proto = ProtocolConfig(mode=C.SWIM, fanout=2, swim_subjects=8,
                           swim_proxies=2, swim_suspect_rounds=t)
    fault = FaultConfig(seed=1, churn=ChurnConfig(
        events=((5, 2, -1),), ramp=(0, 6, 0.0, 0.3)))
    fr, fin = simulate_swim_curve(proto, n, rounds, dead_nodes=(),
                                  fail_round=0, fault=fault)
    status = np.asarray(SW.decode_status(fin.wire))
    obs = np.asarray(SW.observer_alive(n, (), fault))
    assert (status[obs, 5] == SW.DEAD).all(), "crash not confirmed"
    fr2, fin2 = simulate_swim_curve(proto, n, rounds, dead_nodes=(),
                                    fail_round=0, fault=fault,
                                    mesh=make_mesh(4))
    assert np.array_equal(np.asarray(fin.wire),
                          np.asarray(fin2.wire)[:n])
    # ... and the packed-rng lowering accepts the traced threshold too
    pproto = ProtocolConfig(mode=C.SWIM, fanout=2, swim_subjects=8,
                            swim_proxies=2, swim_suspect_rounds=t,
                            swim_rng="packed")
    fr3, fin3 = simulate_swim_curve(pproto, n, 12, dead_nodes=(),
                                    fail_round=0, fault=fault)
    assert np.isfinite(fr3).all()


def test_committed_churn_sweep_record():
    """The committed amortization artifact
    (artifacts/ledger_churn_sweep_r11.jsonl): provenance-carrying; the
    K>=8-scenario dense-sharded warm path beat K solo (fresh-compile)
    reruns by >= 3x; per-scenario round_metrics stacks carry the
    nemesis columns; the batched vmapped sweep ran the same family."""
    from gossip_tpu.utils import telemetry
    path = os.path.join(_REPO, "artifacts",
                        "ledger_churn_sweep_r11.jsonl")
    evs = telemetry.load_ledger(path, run="last")
    assert evs[0]["ev"] == "provenance"
    assert len(evs[0]["git_commit"]) == 40
    rec = [e for e in evs if e.get("ev") == "churn_sweep_record"][-1]
    assert rec["k"] >= 8 and rec["driver"] == "dense_sharded"
    assert rec["accept_3x"] is True
    assert rec["solo_total_ms"] >= 3 * rec["warm_total_ms"]
    assert rec["speedup"] >= 3
    assert rec["batched_warm_ms"] > 0
    # per-scenario nemesis observables rode the drivers' own flushes
    rms = [e for e in evs if e.get("ev") == "round_metrics"]
    assert len(rms) >= rec["k"]
    assert all("alive" in e and "dropped" in e for e in rms)
    scen = [e for e in evs if e.get("ev") == "churn_sweep_scenario"]
    assert len(scen) == rec["k"]
    assert all(s["final_coverage"] == 1.0 for s in scen)
    assert any(s["dropped_total"] > 0 for s in scen)


# -- no-churn pins ----------------------------------------------------

def test_no_churn_configs_stay_bitwise_unchanged():
    """A fault carrying a VACUOUS churn object runs the static hot path
    bitwise (the FaultConfig normalization) — the cheap in-gate twin of
    the full no-churn fingerprint the parity suites pin."""
    from gossip_tpu.runtime.simulator import simulate_curve
    topo = G.complete(64)
    proto = ProtocolConfig(mode=C.PUSH_PULL, fanout=2, rumors=2)
    run = RunConfig(seed=0, max_rounds=6)
    f0 = FaultConfig(node_death_rate=0.1, drop_prob=0.1, seed=1)
    f1 = FaultConfig(node_death_rate=0.1, drop_prob=0.1, seed=1,
                     churn=ChurnConfig())
    a = simulate_curve(proto, topo, run, f0)
    b = simulate_curve(proto, topo, run, f1)
    assert np.array_equal(np.asarray(a.state.seen),
                          np.asarray(b.state.seen))
    assert np.array_equal(a.msgs, b.msgs)


# -- CLI parse --------------------------------------------------------

def test_cli_churn_sweep_command(capsys):
    """The churn-sweep subcommand end to end (in-process main): K
    scenarios through one compiled loop, JSON summaries per scenario,
    and the spec parser's error paths."""
    from gossip_tpu import cli
    rc = cli.main([
        "churn-sweep", "--n", "64", "--max-rounds", "8",
        "--target", "1.0", "--compile-cache", "",
        "--scenario", "event=3:2:5",
        "--scenario", "partition=0:4:32;ramp=0:3:0.0:0.2"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert out["scenarios"] == 2 and out["n"] == 64
    rows = out["churn_sweep"]
    assert rows[0]["scenario"]["events"] == [[3, 2, 5]]
    assert rows[1]["scenario"]["partitions"] == [[0, 4, 32]]
    assert rows[1]["scenario"]["ramp"] == [0, 3, 0.0, 0.2]
    assert all("dropped_total" in r for r in rows)
    # error paths: unknown field, empty scenario, bad device split
    assert cli.main(["churn-sweep", "--n", "64",
                     "--scenario", "bogus=1:2"]) == 2
    assert "unknown scenario field" in capsys.readouterr().err
    assert cli.main(["churn-sweep", "--n", "64",
                     "--scenario", " ; "]) == 2
    assert "scripts no faults" in capsys.readouterr().err
    assert cli.main(["churn-sweep", "--n", "64", "--devices", "3",
                     "--scenario", "event=3:2:5"]) == 2
    assert "do not divide" in capsys.readouterr().err
    # --engine fused: plane-stack eligibility is checked up front with
    # the ONE reason list (backend._fused_ineligible_reason) — on the
    # CPU tier the platform probe refuses cleanly before any driver
    # work (the fused sweep machinery itself is pinned on the virtual
    # mesh in tests/test_sharded_fused.py); a non-pull mode names the
    # mode reason first
    assert cli.main(["churn-sweep", "--n", "64", "--engine", "fused",
                     "--mode", "pull",
                     "--scenario", "event=3:2:5"]) == 2
    assert "TPU" in capsys.readouterr().err
    assert cli.main(["churn-sweep", "--n", "64", "--engine", "fused",
                     "--scenario", "event=3:2:5"]) == 2
    assert "pull" in capsys.readouterr().err


def test_cli_churn_parse():
    import argparse

    from gossip_tpu.cli import _parse_churn
    ns = argparse.Namespace(churn_event=["3:2:5", "7:1"],
                            partition=["0:4:32"],
                            drop_ramp="1:4:0.0:0.3")
    ch = _parse_churn(ns)
    assert ch.events == ((3, 2, 5), (7, 1, -1))
    assert ch.partitions == ((0, 4, 32),)
    assert ch.ramp == (1, 4, 0.0, 0.3)
    assert _parse_churn(argparse.Namespace(
        churn_event=None, partition=None, drop_ramp=None)) is None
    with pytest.raises(ValueError, match="churn-event"):
        _parse_churn(argparse.Namespace(churn_event=["3"],
                                        partition=None, drop_ramp=None))
    with pytest.raises(ValueError, match="partition"):
        _parse_churn(argparse.Namespace(churn_event=None,
                                        partition=["0:4"],
                                        drop_ramp=None))


# -- sidecar transport retry (satellite) ------------------------------

def _fake_rpc_error(code):
    import grpc

    class E(grpc.RpcError):
        def code(self):
            return code

    return E()


def test_sidecar_retries_transient_then_succeeds(tmp_path):
    import grpc

    from gossip_tpu.rpc.sidecar import SidecarClient
    from gossip_tpu.utils import telemetry
    client = SidecarClient("127.0.0.1:1", max_attempts=4,
                           backoff_base=0.001, backoff_cap=0.002)
    calls = []

    def flaky(payload, timeout, metadata=None):
        calls.append(timeout)
        if len(calls) < 3:
            raise _fake_rpc_error(grpc.StatusCode.UNAVAILABLE)
        return b'{"ok": true}'

    path = str(tmp_path / "rpc.jsonl")
    led = telemetry.Ledger(path)
    prev = telemetry.activate(led)
    try:
        out = client._call_with_retry(flaky, b"{}", 1.0, "health")
    finally:
        telemetry.activate(prev)
        led.close()
    assert out == b'{"ok": true}'
    assert len(calls) == 3                      # 2 retries, fresh deadline each
    retries = [e for e in telemetry.load_ledger(path)
               if e.get("ev") == "rpc_retry"]
    assert [e["attempt"] for e in retries] == [1, 2]
    assert all(e["method"] == "health" and "UNAVAILABLE" in e["code"]
               for e in retries)
    client.close()


def test_sidecar_never_retries_well_formed_error_reply():
    import grpc

    from gossip_tpu.rpc.sidecar import SidecarClient
    client = SidecarClient("127.0.0.1:1", max_attempts=4,
                           backoff_base=0.001)
    calls = []

    def invalid(payload, timeout, metadata=None):
        calls.append(1)
        raise _fake_rpc_error(grpc.StatusCode.INVALID_ARGUMENT)

    with pytest.raises(grpc.RpcError):
        client._call_with_retry(invalid, b"{}", 1.0, "run")
    assert len(calls) == 1                       # raised immediately

    # and the attempt cap bounds a dead transport
    dead_calls = []

    def dead(payload, timeout, metadata=None):
        dead_calls.append(1)
        raise _fake_rpc_error(grpc.StatusCode.UNAVAILABLE)

    with pytest.raises(grpc.RpcError):
        client._call_with_retry(dead, b"{}", 1.0, "run")
    assert len(dead_calls) == client.max_attempts
    client.close()
