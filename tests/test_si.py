"""SI round kernels: monotonicity, convergence, parity between modes.

These are the per-kernel unit/property tests the reference never had
(SURVEY.md §4: zero test files in the repo; testing was entirely external
black-box Maelstrom runs).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gossip_tpu import topology as T
from gossip_tpu.config import FaultConfig, ProtocolConfig, RunConfig
from gossip_tpu.models.si import coverage, make_si_round
from gossip_tpu.models.state import init_state
from gossip_tpu.runtime.simulator import simulate_curve, simulate_until


def run_rounds(proto, topo, rounds, seed=0, fault=None):
    step = jax.jit(make_si_round(proto, topo, fault))
    state = init_state(RunConfig(seed=seed), proto, topo.n)
    states = [state]
    for _ in range(rounds):
        state = step(state)
        states.append(state)
    return states


@pytest.mark.parametrize("mode", ["push", "pull", "pushpull"])
def test_monotone_coverage(mode):
    topo = T.complete(256)
    proto = ProtocolConfig(mode=mode, fanout=1)
    states = run_rounds(proto, topo, 25)
    covs = [float(coverage(s.seen)) for s in states]
    assert covs[0] == pytest.approx(1 / 256)
    assert all(b >= a for a, b in zip(covs, covs[1:])), covs
    # ~log2(N)+ln(N) ≈ 14 expected rounds at N=256; 25 is comfortably past
    assert covs[-1] == 1.0


@pytest.mark.parametrize("mode", ["push", "pull", "pushpull"])
def test_converges_on_sparse_graph(mode):
    topo = T.erdos_renyi(512, 0.03, seed=7)
    proto = ProtocolConfig(mode=mode, fanout=2)
    res = simulate_until(proto, topo, RunConfig(max_rounds=128, seed=1))
    assert res.coverage >= 0.99
    assert 0 < res.rounds < 128


def test_pushpull_beats_push():
    """Push-pull converges in fewer rounds than push alone (classic result)."""
    topo = T.complete(4096)
    run = RunConfig(max_rounds=200, seed=3)
    r_push = simulate_until(ProtocolConfig(mode="push", fanout=1), topo, run)
    r_pp = simulate_until(ProtocolConfig(mode="pushpull", fanout=1), topo, run)
    assert r_pp.rounds < r_push.rounds


def test_seen_never_lost():
    """Once seen, always seen (the dedup set only grows, main.go:35-44)."""
    topo = T.ring(128, k=4)
    proto = ProtocolConfig(mode="pushpull", fanout=1)
    states = run_rounds(proto, topo, 20, seed=2)
    prev = np.asarray(states[0].seen)
    for s in states[1:]:
        cur = np.asarray(s.seen)
        assert (cur | prev).sum() == cur.sum()  # prev ⊆ cur
        prev = cur


def test_flood_is_bfs():
    """Flood coverage after t rounds == BFS ball of radius t (Go-parity
    claim from ops/propagate.py docstring) — checked exactly."""
    topo = T.watts_strogatz(200, k=4, beta=0.3, seed=5)
    proto = ProtocolConfig(mode="flood")
    states = run_rounds(proto, topo, 10, seed=0)

    # host-side BFS
    nbrs, deg = np.asarray(topo.nbrs), np.asarray(topo.deg)
    dist = np.full(200, -1)
    dist[0] = 0
    frontier = [0]
    d = 0
    while frontier:
        nxt = []
        for u in frontier:
            for v in nbrs[u, : deg[u]]:
                if dist[v] < 0:
                    dist[v] = d + 1
                    nxt.append(int(v))
        frontier, d = nxt, d + 1

    for t, s in enumerate(states):
        expect = (dist >= 0) & (dist <= t)
        got = np.asarray(s.seen)[:, 0]
        np.testing.assert_array_equal(got, expect), f"round {t}"


def test_multirumor():
    topo = T.complete(512)
    proto = ProtocolConfig(mode="pushpull", fanout=1, rumors=8)
    res = simulate_until(proto, topo, RunConfig(max_rounds=64, seed=4))
    assert res.coverage >= 0.99
    seen = np.asarray(res.state.seen)
    assert seen.shape == (512, 8)


def test_messages_counted():
    topo = T.complete(128)
    res = simulate_curve(ProtocolConfig(mode="push", fanout=2), topo,
                         RunConfig(max_rounds=10, seed=0))
    msgs = res.msgs
    assert (np.diff(msgs) >= 0).all()
    # round 1: exactly one infected node pushes fanout=2 messages
    assert msgs[0] == 2.0
    # pull costs 2 messages per exchange, all nodes pull every round
    res_pull = simulate_curve(ProtocolConfig(mode="pull", fanout=1), topo,
                              RunConfig(max_rounds=3, seed=0))
    assert res_pull.msgs[0] == 2.0 * 128


def test_dead_nodes_never_infected():
    topo = T.complete(256)
    fault = FaultConfig(node_death_rate=0.3, seed=9)
    proto = ProtocolConfig(mode="pushpull", fanout=2)
    res = simulate_until(proto, topo, RunConfig(max_rounds=64, seed=5), fault)
    from gossip_tpu.models.state import alive_mask
    alive = np.asarray(alive_mask(fault, 256, 0))
    seen = np.asarray(res.state.seen)[:, 0]
    assert res.coverage >= 0.99          # alive population still converges
    assert not seen[~alive].any()        # the dead stay dark


def test_drop_prob_slows_but_converges():
    """Lossy links: at-least-once semantics — resent next round, still
    converges (reference retry loop main.go:80-87 without its liveness hole,
    SURVEY.md §2.2.7)."""
    topo = T.complete(512)
    run = RunConfig(max_rounds=256, seed=6)
    clean = simulate_until(ProtocolConfig(mode="push", fanout=1), topo, run)
    lossy = simulate_until(ProtocolConfig(mode="push", fanout=1), topo, run,
                           FaultConfig(drop_prob=0.5, seed=1))
    assert lossy.coverage >= 0.99
    assert lossy.rounds > clean.rounds


def test_anti_entropy_period():
    topo = T.ring(64, k=4)
    proto = ProtocolConfig(mode="antientropy", fanout=1, period=4)
    res = simulate_curve(proto, topo, RunConfig(max_rounds=24))
    covs = res.coverage
    # progress happens only on period boundaries: rounds 1..3 after an
    # exchange round are flat
    for t in range(1, len(covs) - 1):
        if (t % 4) != 0:
            assert covs[t] == covs[t - 1]


def test_anti_entropy_is_bidirectional():
    """Classic anti-entropy reconciles BOTH directions (Demers et al.): with
    the same partner draws, the anti-entropy round infects a superset of the
    pull round (pull + the initiators' reverse deltas), and accounting is 3
    messages per exchange vs pull's 2."""
    import jax
    from gossip_tpu.models.si import make_si_round
    from gossip_tpu.models.state import init_state
    topo = T.complete(256)
    run = RunConfig(max_rounds=8, seed=3)
    pull_p = ProtocolConfig(mode="pull", fanout=1)
    ae_p = ProtocolConfig(mode="antientropy", fanout=1, period=1)
    st_pull = init_state(run, pull_p, topo.n)
    st_ae = init_state(run, ae_p, topo.n)
    step_pull = jax.jit(make_si_round(pull_p, topo))
    step_ae = jax.jit(make_si_round(ae_p, topo))
    for _ in range(6):
        st_pull, st_ae = step_pull(st_pull), step_ae(st_ae)
    # re-run AE from the PULL trajectory's state for a same-state,
    # same-draws one-round comparison
    one_pull = step_pull(st_pull)
    one_ae = step_ae(st_pull)
    sp = np.asarray(one_pull.seen)
    sa = np.asarray(one_ae.seen)
    assert (sp <= sa).all()                       # superset
    assert sa.sum() > sp.sum()                    # reverse delta bites
    dm_pull = float(one_pull.msgs) - float(st_pull.msgs)
    dm_ae = float(one_ae.msgs) - float(st_pull.msgs)
    assert dm_ae == pytest.approx(1.5 * dm_pull)  # 3 vs 2 per exchange


def test_determinism():
    topo = T.erdos_renyi(256, 0.05, seed=11)
    proto = ProtocolConfig(mode="pushpull", fanout=1)
    a = simulate_curve(proto, topo, RunConfig(max_rounds=16, seed=42))
    b = simulate_curve(proto, topo, RunConfig(max_rounds=16, seed=42))
    np.testing.assert_array_equal(a.coverage, b.coverage)
    c = simulate_curve(proto, topo, RunConfig(max_rounds=16, seed=43))
    assert not np.array_equal(a.coverage, c.coverage)
