"""Black-box Maelstrom-protocol conformance (SURVEY.md §2.5 contract).

Drives real ``maelstrom_node`` OS processes over stdin/stdout pipes through
the mini-Maelstrom router — the reference's exact test setup (SURVEY.md §4):
multi-node without a cluster, one process per node, simulated network.
The workload is the Gossip Glomers broadcast checker's invariant: every
broadcast message eventually appears in every node's read.
"""

import asyncio

import pytest

from gossip_tpu.runtime.maelstrom_harness import (
    MaelstromHarness, grid_topology, line_topology)


def run(coro):
    return asyncio.run(coro)


def test_single_node_conformance():
    async def main():
        h = MaelstromHarness(1)
        await h.start()          # init/init_ok exercised inside
        try:
            await h.set_topology({"n0": []})
            r = await h.broadcast("n0", 7)
            assert r["body"]["type"] == "broadcast_ok"
            # reply correlation: in_reply_to must echo the msg_id we sent
            # (the harness allocates ids sequentially from _next_msg_id)
            assert r["body"]["in_reply_to"] == h._next_msg_id
            assert await h.read("n0") == [7]
            # duplicate broadcast: acked, not re-appended (dedup,
            # reference main.go:113)
            await h.broadcast("n0", 7)
            assert await h.read("n0") == [7]
            # unknown type -> Maelstrom error reply, code 10
            err = await h.send_raw("n0", {"type": "frobnicate"})
            assert err["body"]["type"] == "error"
            assert err["body"]["code"] == 10
        finally:
            await h.stop()
    run(main())


def test_line_topology_full_propagation():
    async def main():
        h = MaelstromHarness(5)
        await h.start()
        try:
            await h.set_topology(line_topology(h.ids))
            for v in (1, 2, 3):
                await h.broadcast("n0", v)
            await h.broadcast("n4", 99)      # from the far end too
            await h.quiesce()
            for nid in h.ids:
                assert sorted(await h.read(nid)) == [1, 2, 3, 99], nid
        finally:
            await h.stop()
    run(main())


def test_grid_topology_propagation():
    async def main():
        h = MaelstromHarness(9)
        await h.start()
        try:
            await h.set_topology(grid_topology(h.ids, cols=3))
            for i, v in enumerate((10, 20, 30)):
                await h.broadcast(h.ids[i * 4 % 9], v)
            await h.quiesce()
            for nid in h.ids:
                assert sorted(await h.read(nid)) == [10, 20, 30], nid
        finally:
            await h.stop()
    run(main())


def test_partition_tolerance_retry_heals():
    # The partition-tolerance variant of the workload (SURVEY.md §4): cut
    # the only link to n2, broadcast, heal, and the node's retry loop must
    # deliver (at-least-once; fixed-context variant, maelstrom_node doc).
    async def main():
        h = MaelstromHarness(3, latency=0.002)
        await h.start()
        try:
            await h.set_topology(line_topology(h.ids))
            h.partition("n1", "n2", duration=1.5)
            await h.broadcast("n0", 5)
            await asyncio.sleep(0.3)
            assert await h.read("n1") == [5]     # reached the near side
            assert await h.read("n2") == []      # cut off
            await asyncio.sleep(2.0)             # heal + retry window
            await h.quiesce()
            assert await h.read("n2") == [5]     # retry delivered
        finally:
            await h.stop()
    run(main())
