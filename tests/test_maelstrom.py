"""Black-box Maelstrom-protocol conformance (SURVEY.md §2.5 contract).

Drives real ``maelstrom_node`` OS processes over stdin/stdout pipes through
the mini-Maelstrom router — the reference's exact test setup (SURVEY.md §4):
multi-node without a cluster, one process per node, simulated network.
The workload is the Gossip Glomers broadcast checker's invariant: every
broadcast message eventually appears in every node's read.
"""

import asyncio

import pytest

from gossip_tpu.runtime.maelstrom_harness import (
    MaelstromHarness, grid_topology, line_topology)


def run(coro):
    return asyncio.run(coro)


def test_single_node_conformance():
    async def main():
        h = MaelstromHarness(1)
        await h.start()          # init/init_ok exercised inside
        try:
            await h.set_topology({"n0": []})
            r = await h.broadcast("n0", 7)
            assert r["body"]["type"] == "broadcast_ok"
            # reply correlation: in_reply_to must echo the msg_id we sent
            # (the harness allocates ids sequentially from _next_msg_id)
            assert r["body"]["in_reply_to"] == h._next_msg_id
            assert await h.read("n0") == [7]
            # duplicate broadcast: acked, not re-appended (dedup,
            # reference main.go:113)
            await h.broadcast("n0", 7)
            assert await h.read("n0") == [7]
            # unknown type -> Maelstrom error reply, code 10
            err = await h.send_raw("n0", {"type": "frobnicate"})
            assert err["body"]["type"] == "error"
            assert err["body"]["code"] == 10
        finally:
            await h.stop()
    run(main())


def test_line_topology_full_propagation():
    async def main():
        h = MaelstromHarness(5)
        await h.start()
        try:
            await h.set_topology(line_topology(h.ids))
            for v in (1, 2, 3):
                await h.broadcast("n0", v)
            await h.broadcast("n4", 99)      # from the far end too
            await h.quiesce()
            for nid in h.ids:
                assert sorted(await h.read(nid)) == [1, 2, 3, 99], nid
        finally:
            await h.stop()
    run(main())


def test_grid_topology_propagation():
    async def main():
        h = MaelstromHarness(9)
        await h.start()
        try:
            await h.set_topology(grid_topology(h.ids, cols=3))
            for i, v in enumerate((10, 20, 30)):
                await h.broadcast(h.ids[i * 4 % 9], v)
            await h.quiesce()
            for nid in h.ids:
                assert sorted(await h.read(nid)) == [10, 20, 30], nid
        finally:
            await h.stop()
    run(main())


class _StubNode:
    """Scripted peer for gossip retry-policy unit tests."""

    node_id = "n0"

    def __init__(self, replies):
        self.replies = list(replies)    # body types to return, last repeats
        self.calls = 0

    def handle(self, typ, fn):
        pass

    async def rpc(self, dest, body, timeout=2.0):
        i = min(self.calls, len(self.replies) - 1)
        self.calls += 1
        return {"src": dest, "body": {"type": self.replies[i]}}


def test_error_reply_is_retried_not_treated_as_ack():
    # The reference's SyncRPC surfaces an error reply as a Go error and the
    # retry loop keeps going (main.go:81-87); a matched reply of type
    # "error" must NOT count as delivery.
    from gossip_tpu.runtime.maelstrom_node import BroadcastServer
    async def main():
        node = _StubNode(["error", "error", "broadcast_ok"])
        srv = BroadcastServer(node, backoff_base=0.0)
        srv.topology = {"n0": ["n1"]}
        await srv.gossip(5, exclude="nX")
        assert node.calls == 3          # two error replies retried
    run(main())


def test_retry_exhaustion_warns_on_stderr(capsys):
    from gossip_tpu.runtime.maelstrom_node import BroadcastServer
    async def main():
        node = _StubNode(["error"])
        srv = BroadcastServer(node, backoff_base=0.0, max_retries=4)
        srv.topology = {"n0": ["n1"]}
        await srv.gossip(9, exclude="nX")
        assert node.calls == 4
    run(main())
    assert "giving up on n1" in capsys.readouterr().err


def test_partition_tolerance_retry_heals():
    # The partition-tolerance variant of the workload (SURVEY.md §4): cut
    # the only link to n2, broadcast, heal, and the node's retry loop must
    # deliver (at-least-once; fixed-context variant, maelstrom_node doc).
    async def main():
        h = MaelstromHarness(3, latency=0.002)
        await h.start()
        try:
            await h.set_topology(line_topology(h.ids))
            h.partition("n1", "n2", duration=1.5)
            await h.broadcast("n0", 5)
            await asyncio.sleep(0.3)
            assert await h.read("n1") == [5]     # reached the near side
            assert await h.read("n2") == []      # cut off
            await asyncio.sleep(2.0)             # heal + retry window
            await h.quiesce()
            assert await h.read("n2") == [5]     # retry delivered
        finally:
            await h.stop()
    run(main())


# slow tier (tier-1 wall budget): the broadcast invariant stays
# gated via test_grid_topology_propagation + interval batching
@pytest.mark.slow
def test_broadcast_workload_stats_and_invariant():
    """The in-repo Maelstrom 'broadcast' workload: random-node ops at a
    rate, quiesce, per-node reads — the checker invariant plus the
    checker-style stats (msgs-per-op, op latencies)."""
    from gossip_tpu.runtime.maelstrom_harness import run_broadcast_workload
    stats = asyncio.run(run_broadcast_workload(
        4, ops=8, rate=100.0, latency=0.001, seed=2))
    assert stats["invariant_ok"] is True
    assert stats["broadcast_ops"] == 8
    assert stats["msgs_per_op"] > 0
    assert stats["op_latency_ms"]["p99"] >= stats["op_latency_ms"]["p50"] > 0
    # fault-tolerance variant: invariant must hold THROUGH a partition
    # (the nodes' retry loops heal the cut)
    stats_p = asyncio.run(run_broadcast_workload(
        4, ops=8, rate=25.0, latency=0.001, partition_mid=True, seed=3))
    assert stats_p["invariant_ok"] is True
    assert stats_p["partitioned"] is True


# depth tier (tier-1 wall budget, PR 7 rebalance): the batching layer
# keeps its contract smokes in-gate; the msgs-per-op reduction
# acceptance (pinned on the committed batching artifacts) runs under
# -m slow
@pytest.mark.slow
def test_interval_batching_cuts_msgs_per_op():
    """The efficiency variant the reference never addressed (VERDICT r3
    item 7): interval-batched relays must pass the same checker
    invariant with FEWER inter-node messages per op than the immediate
    fan-out — values share batches instead of each riding its own
    broadcast+ack chain per edge."""
    import sys

    from gossip_tpu.runtime.maelstrom_harness import run_broadcast_workload
    batched_argv = [sys.executable, "-u", "-m",
                    "gossip_tpu.runtime.maelstrom_node",
                    "--gossip-interval", "0.05"]
    # high op rate so many values land inside one 50 ms tick
    immediate = asyncio.run(run_broadcast_workload(
        5, ops=20, rate=200.0, latency=0.001, seed=4))
    batched = asyncio.run(run_broadcast_workload(
        5, ops=20, rate=200.0, latency=0.001, seed=4, argv=batched_argv))
    assert immediate["invariant_ok"] and batched["invariant_ok"]
    assert batched["msgs_per_op"] < immediate["msgs_per_op"]
    # on a 5-node line at this rate, batching should be WELL under the
    # immediate path, not marginally (ticks amortize ~10 values each)
    assert batched["msgs_per_op"] < 0.6 * immediate["msgs_per_op"]


def test_batched_node_survives_partition():
    # at-least-once through a cut: unacked batches retry every tick
    import sys

    from gossip_tpu.runtime.maelstrom_harness import run_broadcast_workload
    batched_argv = [sys.executable, "-u", "-m",
                    "gossip_tpu.runtime.maelstrom_node",
                    "--gossip-interval", "0.05"]
    stats = asyncio.run(run_broadcast_workload(
        4, ops=8, rate=25.0, latency=0.001, partition_mid=True, seed=3,
        argv=batched_argv))
    assert stats["invariant_ok"] is True and stats["partitioned"] is True


def test_immediate_node_relays_received_batch_without_flusher():
    """A default-mode node (interval 0) receiving a 'gossip' batch from a
    batched peer must relay through its immediate path and never start
    the tick flusher (interval 0 would busy-spin it)."""
    from gossip_tpu.runtime.maelstrom_node import (BroadcastServer,
                                                   MaelstromNode)

    async def main():
        node = MaelstromNode()
        node.node_id = "n0"
        srv = BroadcastServer(node, gossip_interval=0.0)
        srv.topology = {"n0": ["n1", "n2"]}
        sent = []

        async def fake_reply(msg, body):
            sent.append(("reply", body["type"]))

        async def fake_rpc(dest, body, timeout=2.0):
            sent.append((dest, body["type"], tuple(body.get("messages",
                                                            ()))or
                         body.get("message")))
            return {"body": {"type": "broadcast_ok"}}

        node.reply = fake_reply
        node.rpc = fake_rpc
        await srv.on_gossip({"src": "n1",
                             "body": {"type": "gossip",
                                      "messages": [7, 8]}})
        assert srv._flusher is None           # no busy-spin flusher
        assert srv.messages == [7, 8]
        # relayed to the non-sender neighbor only, via immediate RPCs
        relays = [s for s in sent if s[0] == "n2"]
        assert [r[2] for r in relays] == [7, 8]
        assert not any(s[0] == "n1" for s in sent if s[0] != "reply")
    run(main())
