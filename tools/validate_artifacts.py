#!/usr/bin/env python
"""CI gate: every committed artifact parses, every new-format artifact
carries provenance.

The round-ledger contract (round 7, docs/OBSERVABILITY.md): an
artifact whose numbers are meant to be believed must say which commit,
toolchain, and run produced them — the provenance keys ``run_id``,
``git_commit``, ``captured`` (utils/telemetry.provenance).  Ledger
JSONLs carry them on their first ``provenance`` event line; plain-JSON
artifacts embed the dict under a ``"provenance"`` key (or the three
keys at top level, the bench ``last_tpu`` style).

Artifacts that predate the ledger are ALLOWLISTED BY NAME below — an
explicit, reviewable list, not a silent grandfather clause: adding a
new artifact without provenance fails loudly, and retiring a legacy
file shrinks the list.  Every file, legacy or not, must still parse
(torn jsonl lines — a killed writer's fragment, tail or mid-file in
shared flight-recorder files — are dropped by the crash contract; the
surviving lines must satisfy the schema).

    python tools/validate_artifacts.py            # repo artifacts/
    python tools/validate_artifacts.py DIR        # any directory

Exit 0 all green; exit 1 with one line per failure.  Run in tier-1 by
tests/test_validate_artifacts.py.
"""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PROVENANCE_KEYS = ("run_id", "git_commit", "captured")

# Pre-ledger artifacts, frozen by name.  Do NOT add new files here —
# new artifacts must carry provenance (utils/telemetry.provenance);
# this list only shrinks.
LEGACY = frozenset({
    "baseline_sweep_r02.jsonl",
    "baseline_sweep_r04.jsonl",
    "baseline_sweep_r04.smoke.jsonl",
    "baseline_sweep_r04b.jsonl",
    "baseline_sweep_r05.smoke.jsonl",
    "dryrun_steady_budget_r06.json",
    "ensembles_r05.smoke.json",
    "hw_refresh_r04.json",
    "hw_refresh_r04.smoke.json",
    "hw_refresh_r05.smoke.json",
    "kernel_numbers_r05.smoke.json",
    "maelstrom_batching_r04.json",
    "maelstrom_batching_r05.json",
    "parity_r03.json",
    "parity_r04.json",
    "parity_r05.json",
    "roofline_r05.smoke.json",
    "swim_ab_r04.json",
    "swim_cache_r04.json",
    "swim_compile_ablation_r04.json",
    "swim_diss_ab_r04.smoke.json",
    "swim_diss_ab_r05.smoke.json",
    # swim_steady_ablation_r05.smoke.json left this list in the
    # observability PR: the tool now embeds provenance and the
    # committed smoke artifact was regenerated with it
    "tunnel_health_r04.jsonl",
    "tunnel_health_r05.jsonl",
})


def _parse_jsonl(path):
    """Parsed lines via the ONE crash-contract parser
    (utils/telemetry.load_ledger: torn lines dropped — tail for
    single-writer ledgers, mid-file for shared flight-recorder files)
    — the contract must not fork between the writer and this gate."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    try:
        from _telemetry import telemetry
    finally:
        sys.path.pop(0)
    return telemetry().load_ledger(path)


def _has_provenance_keys(obj) -> bool:
    if not isinstance(obj, dict):
        return False
    if all(k in obj for k in PROVENANCE_KEYS):
        return True
    prov = obj.get("provenance")
    return isinstance(prov, dict) and all(k in prov
                                          for k in PROVENANCE_KEYS)


def _is_nemesis_name(name: str) -> bool:
    """Churn/nemesis/crashloop/CRDT scenario artifacts by name —
    robustness evidence (heal convergence, fault observables,
    SIGKILL/resume records, value-convergence verdicts) must always be
    attributable; the legacy allowlist can never grandfather one in
    (the whole nemesis layer, the crashloop harness, and the CRDT
    subsystem all post-date the provenance schema)."""
    return ("churn" in name or "nemesis" in name
            or "crashloop" in name or "crdt" in name)


def _is_byz_name(name: str) -> bool:
    """Byzantine-adversary artifacts by name — the liar-scenario
    evidence (defended honest-set convergence vs the undefended
    control arm, quorum parameters, mesh-parity verdicts —
    ops/nemesis byz programs via tools/byzantine_capture) must always
    be attributable; the legacy allowlist can never grandfather one
    in (the whole byzantine layer post-dates the provenance schema).
    An unattributed adversary record is the exact claim the defense
    lattice exists to reject: state nobody can trace to a writer."""
    return ("byz" in name or "byzantine" in name
            or "adversary" in name)


def _is_log_name(name: str) -> bool:
    """Replicated-log ("kafka") artifacts by name — log-convergence
    verdicts and workload invariant records (the ordered
    eventual-consistency evidence, ops/logs + the KafkaServer
    workload) must always be attributable; the legacy allowlist can
    never grandfather one in (the whole log subsystem post-dates the
    provenance schema)."""
    return "kafka" in name or "replog" in name


def _is_txn_name(name: str) -> bool:
    """Txn/register artifacts by name — isolation-anomaly verdicts and
    LWW convergence records (the totally-available-transactions
    evidence, ops/registers + the TxnServer workload +
    runtime/txn_checker) must always be attributable; the legacy
    allowlist can never grandfather one in (the whole register
    subsystem post-dates the provenance schema)."""
    return "txn" in name or "register" in name


def _is_fused_sweep_name(name: str) -> bool:
    """Fused-sweep artifacts by name — the fused engine's
    compile-amortization evidence (K scenarios through one executable,
    warm-vs-solo-recompile ratios — tools/fused_sweep_capture) must
    always be attributable; the legacy allowlist can never grandfather
    one in (the fused-operand layer post-dates the provenance
    schema)."""
    return "fused_sweep" in name


def _is_staticcheck_name(name: str) -> bool:
    """Staticcheck/lint artifacts by name — the invariant analyzer's
    own verdict ledgers (clean-tree claims, per-checker finding
    counts — gossip_tpu/analysis + tools/staticcheck.py) must always
    be attributable; the legacy allowlist can never grandfather one
    in (the analyzer post-dates the provenance schema by fifteen
    rounds, and a lint verdict nobody can attribute to a commit
    certifies nothing)."""
    return "staticcheck" in name or "lint" in name


def _is_scale_name(name: str) -> bool:
    """Scale-planner artifacts by name — capacity plans, HBM budget
    verdicts, and streamed-tiling records (gossip_tpu/planner +
    tools/scale_capture) must always be attributable; the legacy
    allowlist can never grandfather one in (the whole planner
    subsystem post-dates the provenance schema).  The ONE name-space
    collision is carved out explicitly rather than allowlisted:
    dryrun_steady_budget_r06.json is the round-6 dry-run STEADY-WALL
    budget snapshot (docs/PERF.md cites it as before/after evidence),
    not a scale-planner budget — it predates the subsystem by
    fourteen rounds and stays on the ordinary legacy list above."""
    if name == "dryrun_steady_budget_r06.json":
        return False
    return "scale" in name or "plan" in name or "budget" in name


def _is_fleet_name(name: str) -> bool:
    """Fleet/router/failover artifacts by name — the replicated-
    serving evidence (SIGKILLed replicas with zero acked-request loss,
    bitwise failover replay parity, recovery to full capacity —
    rpc/router + tools/fleet_crashloop) must always be attributable;
    the legacy allowlist can never grandfather one in (the whole fleet
    layer post-dates the provenance schema)."""
    return ("fleet" in name or "router" in name
            or "failover" in name)


def _is_serving_name(name: str) -> bool:
    """Serving/load/meshserve artifacts by name — throughput and
    latency gates (the admission-batching layer's committed evidence:
    requests/sec, p50/p95/p99, bitwise-equality verdicts —
    tools/load_harness, including the mesh-sharded device-scaling
    captures) must always be attributable; the legacy allowlist can
    never grandfather one in (the whole serving layer post-dates the
    provenance schema)."""
    return "serving" in name or "load" in name or "meshserve" in name


def _is_cost_name(name: str) -> bool:
    """Cost/xprof/attribution artifacts by name — the XLA cost &
    memory attribution evidence (per-executable flops/bytes, cache
    verdicts, the packed budget_xcheck measured≤predicted pair —
    utils/compile_cache's xla_compile events via tools/cost_capture)
    must always be attributable; the legacy allowlist can never
    grandfather one in (the whole attribution plane post-dates the
    provenance schema).  An unattributed cost table is the exact
    failure the plane exists to prevent: numbers nobody can pin to a
    commit or a compile."""
    return ("cost" in name or "xprof" in name
            or "attribution" in name)


def _is_trace_name(name: str) -> bool:
    """Trace/fleet-status artifacts by name — the request-tracing and
    live-metrics evidence (per-request waterfalls joined by trace_id,
    fleet health snapshots — tools/trace_report, tools/trace_capture,
    `gossip_tpu fleet-status --out`) must always be attributable; the
    legacy allowlist can never grandfather one in (the whole tracing
    plane post-dates the provenance schema).  An unattributed
    waterfall is worse than none: it LOOKS like per-request evidence
    while naming no commit anyone can reproduce it against."""
    return "trace" in name or "fleet_status" in name


def validate_file(path):
    """[] when valid, else a list of human-readable problems."""
    name = os.path.basename(path)
    problems = []
    try:
        if name.endswith(".jsonl"):
            rows = _parse_jsonl(path)
            with open(path) as f:
                nonblank = sum(1 for ln in f if ln.strip())
            if nonblank and not rows:
                # torn-line tolerance must not bless a file with NO
                # surviving lines — that is destruction, not a crash
                problems.append("does not parse: no parseable lines "
                                f"among {nonblank}")
            has_prov = any(_has_provenance_keys(r) for r in rows
                           if isinstance(r, dict))
            if name not in LEGACY and not has_prov:
                problems.append(
                    "new-format jsonl without a provenance line "
                    f"carrying {PROVENANCE_KEYS} "
                    "(utils/telemetry.provenance)")
            # round-metric series are protocol-semantics evidence
            # (ops/round_metrics) and post-date the ledger by two
            # rounds: an artifact carrying them MUST be attributable,
            # allowlist or not — the legacy list can never grandfather
            # a metrics-bearing file in
            if not has_prov and any(
                    isinstance(r, dict)
                    and r.get("ev") == "round_metrics" for r in rows):
                problems.append(
                    "carries round_metrics events but no provenance "
                    "line — round-metric artifacts must be "
                    "attributable (utils/telemetry.provenance)")
            if not has_prov and _is_nemesis_name(name):
                problems.append(
                    "nemesis/churn artifact without a provenance line "
                    "— robustness evidence must be attributable, "
                    "allowlist or not (utils/telemetry.provenance)")
            if not has_prov and _is_serving_name(name):
                problems.append(
                    "serving/load artifact without a provenance line "
                    "— throughput/latency gates must be attributable, "
                    "allowlist or not (utils/telemetry.provenance)")
            if not has_prov and _is_fleet_name(name):
                problems.append(
                    "fleet/router/failover artifact without a "
                    "provenance line — replicated-serving evidence "
                    "must be attributable, allowlist or not "
                    "(utils/telemetry.provenance)")
            if not has_prov and _is_log_name(name):
                problems.append(
                    "replicated-log/kafka artifact without a "
                    "provenance line — log-convergence evidence must "
                    "be attributable, allowlist or not "
                    "(utils/telemetry.provenance)")
            if not has_prov and _is_txn_name(name):
                problems.append(
                    "txn/register artifact without a provenance line "
                    "— isolation-anomaly and LWW-convergence "
                    "evidence must be attributable, allowlist or not "
                    "(utils/telemetry.provenance)")
            if not has_prov and _is_byz_name(name):
                problems.append(
                    "byzantine/adversary artifact without a "
                    "provenance line — liar-scenario evidence must "
                    "be attributable, allowlist or not "
                    "(utils/telemetry.provenance)")
            if not has_prov and _is_fused_sweep_name(name):
                problems.append(
                    "fused-sweep artifact without a provenance line — "
                    "compile-amortization evidence must be "
                    "attributable, allowlist or not "
                    "(utils/telemetry.provenance)")
            if not has_prov and _is_staticcheck_name(name):
                problems.append(
                    "staticcheck/lint artifact without a provenance "
                    "line — an invariant-analyzer verdict must be "
                    "attributable, allowlist or not "
                    "(utils/telemetry.provenance)")
            if not has_prov and _is_scale_name(name):
                problems.append(
                    "scale/plan/budget artifact without a provenance "
                    "line — capacity plans and streamed-tiling "
                    "records must be attributable, allowlist or not "
                    "(utils/telemetry.provenance)")
            if not has_prov and _is_trace_name(name):
                problems.append(
                    "trace/fleet_status artifact without a provenance "
                    "line — per-request waterfalls and fleet health "
                    "snapshots must be attributable, allowlist or not "
                    "(utils/telemetry.provenance)")
            if not has_prov and _is_cost_name(name):
                problems.append(
                    "cost/xprof/attribution artifact without a "
                    "provenance line — XLA cost & memory attribution "
                    "evidence must be attributable, allowlist or not "
                    "(utils/telemetry.provenance)")
        else:
            with open(path) as f:
                doc = json.load(f)
            if _is_nemesis_name(name) and not _has_provenance_keys(doc):
                problems.append(
                    "nemesis/churn artifact without provenance keys "
                    f"{PROVENANCE_KEYS} — robustness evidence must be "
                    "attributable, allowlist or not")
            elif _is_serving_name(name) \
                    and not _has_provenance_keys(doc):
                problems.append(
                    "serving/load artifact without provenance keys "
                    f"{PROVENANCE_KEYS} — throughput/latency gates "
                    "must be attributable, allowlist or not")
            elif _is_fleet_name(name) and not _has_provenance_keys(doc):
                problems.append(
                    "fleet/router/failover artifact without "
                    f"provenance keys {PROVENANCE_KEYS} — replicated-"
                    "serving evidence must be attributable, allowlist "
                    "or not")
            elif _is_log_name(name) and not _has_provenance_keys(doc):
                problems.append(
                    "replicated-log/kafka artifact without provenance "
                    f"keys {PROVENANCE_KEYS} — log-convergence "
                    "evidence must be attributable, allowlist or not")
            elif _is_txn_name(name) and not _has_provenance_keys(doc):
                problems.append(
                    "txn/register artifact without provenance keys "
                    f"{PROVENANCE_KEYS} — isolation-anomaly and "
                    "LWW-convergence evidence must be attributable, "
                    "allowlist or not")
            elif _is_byz_name(name) and not _has_provenance_keys(doc):
                problems.append(
                    "byzantine/adversary artifact without provenance "
                    f"keys {PROVENANCE_KEYS} — liar-scenario evidence "
                    "must be attributable, allowlist or not")
            elif _is_fused_sweep_name(name) \
                    and not _has_provenance_keys(doc):
                problems.append(
                    "fused-sweep artifact without provenance keys "
                    f"{PROVENANCE_KEYS} — compile-amortization "
                    "evidence must be attributable, allowlist or not")
            elif _is_staticcheck_name(name) \
                    and not _has_provenance_keys(doc):
                problems.append(
                    "staticcheck/lint artifact without provenance "
                    f"keys {PROVENANCE_KEYS} — an invariant-analyzer "
                    "verdict must be attributable, allowlist or not")
            elif _is_scale_name(name) and not _has_provenance_keys(doc):
                problems.append(
                    "scale/plan/budget artifact without provenance "
                    f"keys {PROVENANCE_KEYS} — capacity plans and "
                    "streamed-tiling records must be attributable, "
                    "allowlist or not")
            elif _is_trace_name(name) and not _has_provenance_keys(doc):
                problems.append(
                    "trace/fleet_status artifact without provenance "
                    f"keys {PROVENANCE_KEYS} — per-request waterfalls "
                    "and fleet health snapshots must be attributable, "
                    "allowlist or not")
            elif _is_cost_name(name) and not _has_provenance_keys(doc):
                problems.append(
                    "cost/xprof/attribution artifact without "
                    f"provenance keys {PROVENANCE_KEYS} — XLA cost & "
                    "memory attribution evidence must be attributable, "
                    "allowlist or not")
            elif name not in LEGACY and not _has_provenance_keys(doc):
                problems.append(
                    "new-format json without provenance keys "
                    f"{PROVENANCE_KEYS} (embed utils/telemetry."
                    "provenance() under a 'provenance' key)")
    except ValueError as e:
        problems.append(f"does not parse: {e}")
    except OSError as e:
        problems.append(f"unreadable: {e}")
    return problems


def validate_dir(art_dir):
    """{filename: [problems]} for every *.json / *.jsonl in the dir
    (empty dict == all green).  Non-JSON artifacts (.txt/.log capture
    transcripts) are out of scope."""
    failures = {}
    for name in sorted(os.listdir(art_dir)):
        if not name.endswith((".json", ".jsonl")):
            continue
        problems = validate_file(os.path.join(art_dir, name))
        if problems:
            failures[name] = problems
    return failures


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    art_dir = argv[0] if argv else os.path.join(REPO, "artifacts")
    if not os.path.isdir(art_dir):
        print(f"no such directory: {art_dir}", file=sys.stderr)
        return 2
    failures = validate_dir(art_dir)
    checked = [n for n in sorted(os.listdir(art_dir))
               if n.endswith((".json", ".jsonl"))]
    for name, problems in failures.items():
        for p in problems:
            print(f"FAIL {name}: {p}")
    print(f"{len(checked) - len(failures)}/{len(checked)} artifacts "
          f"valid in {art_dir}")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
