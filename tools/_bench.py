"""Single-source loader for the repo-root ``bench.py`` (which is a
standalone script, not a package member — the driver contract pins it at
the repo root, so it cannot simply be imported by name from here).

Every tool that needs bench's hermetic CPU env or budget arithmetic goes
through this module, so the load mechanism — like the wedge-hazard list
it fetches — lives in exactly one place.
"""

import importlib.util
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def hermetic_cpu_env():
    """bench.py's CPU env with the tunnel plugin disarmed (the
    sitecustomize-preloaded TPU tunnel hangs ANY armed jax init while
    wedged, even under JAX_PLATFORMS=cpu)."""
    return load_bench()._hermetic_cpu_env()
