#!/usr/bin/env python
"""Capture the streamed bit-plane scale record (the scale-planner PR's
acceptance artifact).

The CPU-feasible STRUCTURAL record: N = 2^20 nodes x 256 rumors
(8 word planes) planned against an artificially tiny HBM budget that
forces >= 4-tile streaming, run through the full streamed executor
(planner/stream.run_at_scale) under a MIXED fault program
(crash/recover event + permanent crash + open partition window +
drop-rate ramp), with four gates:

  * ``tiles >= 4``                — the plan actually streamed;
  * ``bitwise_equal``             — the T-tile streamed trajectory is
    byte-identical to the untiled in-memory run (final state, msgs,
    AND the exact ``dropped`` total);
  * ``coverage == 1.0``           — on the EVENTUAL-alive set (the
    churn convergence denominator, ops/nemesis.metric_alive);
  * ``measured <= predicted``     — the tile loop's AOT memory
    analysis lands inside the planner's predicted peak device bytes
    (the budget model's honesty gate);

plus a crash-safety leg: the run is repeated with a halt after its
first checkpoint segment and resumed, and the resumed final state must
equal the uninterrupted one bitwise (the utils/checkpoint cursor
discipline through the streamed driver).

Everything lands in ONE run ledger (utils/telemetry — provenance first
line), so the committed artifact passes tools/validate_artifacts.py's
scale/plan/budget provenance gate.

    python tools/scale_capture.py [OUT.jsonl]    # default
        artifacts/ledger_scale_r20.jsonl
    python tools/scale_capture.py --smoke        # CPU rehearsal at
        2^14 nodes, .smoke-infixed artifact (hw_refresh convention)
    python tools/scale_capture.py --full-scale   # the 100M-node leg:
        plans against the DETECTED device topology and executes, into
        its own artifact (ledger_scale_full.jsonl — the structural
        record's run="last" readers must keep seeing a scale_record);
        refuses rc 1 off-TPU (real HBM only; rc 2 stays the hw_refresh
        wedge signature — ROADMAP item 3's hardware-capture remainder,
        run by the hw_refresh scale_plan step at the first healthy
        window)

Platform: ambient (the hw_refresh convention) — the committed record
on this container is the CPU structural proof; the same tool at a TPU
window measures real HBM numbers.
"""

import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N = 2**20
RUMORS = 256            # 8 word planes -> 4 tiles at the forced budget
FANOUT = 2
MAX_ROUNDS = 40
SEGMENT_EVERY = 10
SMOKE_N = 2**14
SMOKE_ROUNDS = 24
FULL_SCALE_N = 100_000_000


def mixed_fault(n):
    """The crashloop-style mixed program: crash/recover + permanent
    crash + open partition window + drop ramp, sized so coverage 1.0
    on the eventual-alive set is reachable inside MAX_ROUNDS."""
    from gossip_tpu.config import ChurnConfig, FaultConfig
    return FaultConfig(drop_prob=0.02, seed=2, churn=ChurnConfig(
        events=((3, 2, 8), (11, 3, -1)),
        partitions=((4, 10, n // 2),),
        ramp=(0, 6, 0.0, 0.15)))


def forced_plan(n, rounds, *, tiles_at_least=4):
    """Plan ``n`` against an HBM budget that forces >=
    ``tiles_at_least`` streamed tiles (the ONE shared construction,
    planner/budget.forced_device_for_tiles — the budget is recorded
    in the artifact; nothing about the trajectory depends on it)."""
    from gossip_tpu.planner import budget as PB
    fault = mixed_fault(n)
    dev = PB.forced_device_for_tiles(
        n, rumors=RUMORS, fanout=FANOUT, max_rounds=rounds,
        fault=fault, tiles_at_least=tiles_at_least)
    return PB.plan_scale(n, rumors=RUMORS, device=dev, fanout=FANOUT,
                         max_rounds=rounds, fault=fault,
                         segment_every=SEGMENT_EVERY)


def full_scale(led) -> int:
    """The 100M-node hardware leg: plan against the DETECTED topology
    and execute.  Gated on real TPU HBM — on any other backend this is
    a structural no-op refused rc 1 (rc 2 would read as the hw_refresh
    wedge signature; the hw_refresh step only passes --full-scale at a
    TPU window)."""
    import jax
    from gossip_tpu.planner import budget as PB
    from gossip_tpu.planner.stream import run_at_scale
    if jax.default_backend() != "tpu":
        # rc 1, not 2: off-TPU --full-scale is an operator error, and
        # rc 2 is the hw_refresh wedge-signature convention
        print(json.dumps({"error": "full-scale needs real TPU HBM",
                          "backend": jax.default_backend()}))
        return 1
    devs = jax.devices()
    stats = devs[0].memory_stats() or {}
    hbm = int(stats.get("bytes_limit", 16 * 1024**3))
    from gossip_tpu.parallel.multislice import detect_slices
    dev = PB.DeviceSpec(chips=len(devs), hbm_bytes_per_chip=hbm,
                        slices=detect_slices(devs))
    plan = PB.plan_scale(FULL_SCALE_N, rumors=64, device=dev,
                         fanout=FANOUT, max_rounds=64,
                         fault=mixed_fault(FULL_SCALE_N))
    led.event("scale_full_plan", **{
        "n": plan.n, "tiles": plan.tiles,
        "bucket_words": plan.bucket_words,
        "chips": dev.chips, "hbm_bytes_per_chip": hbm,
        "slices": dev.slices,
        "predicted_peak_device_bytes":
            plan.predicted_peak_device_bytes})
    res = run_at_scale(plan, measure_memory=True)
    led.event("scale_full_run", rounds=res.rounds,
              coverage=res.coverage, tiles=res.tiles,
              measured_loop_bytes=res.measured_loop_bytes)
    print(json.dumps({"full_scale": res.to_dict()}))
    return 0 if res.coverage == 1.0 else 1


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    smoke = "--smoke" in argv
    full = "--full-scale" in argv
    argv = [a for a in argv if a not in ("--smoke", "--full-scale")]
    infix = ".smoke" if smoke else ""
    # the full-scale leg gets its OWN artifact: appending a run with
    # no scale_record event to the structural record would break its
    # run="last" readers (bench.last_scale_record, the tier-1 pin)
    default_name = (f"ledger_scale_full{infix}.jsonl" if full
                    else f"ledger_scale_r20{infix}.jsonl")
    out_path = (argv[0] if argv else
                os.path.join(REPO, "artifacts", default_name))
    if smoke:
        os.environ["JAX_PLATFORMS"] = "cpu"
    n = SMOKE_N if smoke else N
    rounds = SMOKE_ROUNDS if smoke else MAX_ROUNDS

    import numpy as np

    import jax
    from gossip_tpu.planner.stream import run_at_scale
    from gossip_tpu.utils import telemetry

    led = telemetry.Ledger(out_path)
    prev = telemetry.activate(led)
    try:
        led.record_runtime()
        if full:
            return full_scale(led)
        plan = forced_plan(n, rounds)
        t0 = time.perf_counter()
        res = run_at_scale(plan, check_bitwise=True,
                           measure_memory=True, keep_state=True)
        streamed_ms = (time.perf_counter() - t0) * 1e3

        # crash-safety leg: halt after the first published segment,
        # resume, and land bitwise on the uninterrupted run
        with tempfile.TemporaryDirectory() as td:
            ck = os.path.join(td, "scale_ck.npz")
            run_at_scale(plan, checkpoint_path=ck,
                         halt_after_segments=1)
            r2 = run_at_scale(plan, checkpoint_path=ck, resume=True,
                              keep_state=True)
        resume_bitwise = (np.array_equal(r2.final_state,
                                         res.final_state)
                          and r2.dropped == res.dropped
                          and r2.msgs == res.msgs)

        gates = {
            "tiles_ge_4": res.tiles >= 4,
            "bitwise_equal": res.bitwise_equal is True,
            "coverage_1": res.coverage == 1.0,
            "memory_within_prediction":
                res.measured_loop_bytes is not None
                and res.measured_loop_bytes
                <= res.predicted_peak_device_bytes,
            "resume_bitwise": resume_bitwise,
        }
        ok = all(gates.values())
        led.event("scale_record",
                  n=n, rumors=RUMORS, fanout=FANOUT, rounds=res.rounds,
                  tiles=res.tiles, bucket_words=res.bucket_words,
                  total_words=plan.total_words,
                  segments=res.segments_run,
                  backend=jax.default_backend(), smoke=smoke,
                  hbm_budget_bytes=plan.hbm_budget_bytes,
                  predicted_peak_device_bytes=
                  res.predicted_peak_device_bytes,
                  measured_loop_bytes=res.measured_loop_bytes,
                  coverage=res.coverage, msgs=res.msgs,
                  dropped=res.dropped,
                  streamed_wall_ms=round(streamed_ms, 1),
                  binding=plan.binding, ok=ok, **gates)
        print(json.dumps({"n": n, "tiles": res.tiles,
                          "coverage": res.coverage,
                          "measured_loop_bytes": res.measured_loop_bytes,
                          "predicted_peak_device_bytes":
                          res.predicted_peak_device_bytes,
                          "backend": jax.default_backend(),
                          "ok": ok, "gates": gates,
                          "ledger": out_path}))
        return 0 if ok else 1
    finally:
        telemetry.activate(prev)
        led.close()


if __name__ == "__main__":
    sys.exit(main())
