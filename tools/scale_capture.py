#!/usr/bin/env python
"""Capture the streamed bit-plane scale record (the scale-planner PR's
acceptance artifact).

The CPU-feasible STRUCTURAL record: N = 2^20 nodes x 256 rumors
(8 word planes) planned against an artificially tiny HBM budget that
forces >= 4-tile streaming, run through the full streamed executor
(planner/stream.run_at_scale) — the THREE-STAGE PIPELINE: tile k
computes while k+1's words transfer in and k-1's result drains out —
under a MIXED fault program (crash/recover event + permanent crash +
open partition window + drop-rate ramp), with these gates:

  * ``tiles >= 4``                — the plan actually streamed;
  * ``bitwise_equal``             — the T-tile streamed trajectory is
    byte-identical to the untiled in-memory run (final state, msgs,
    AND the exact ``dropped`` total);
  * ``no_overlap_bitwise``        — the A/B leg: the same plan re-run
    with ``overlap=False`` (immediate per-tile drain, no pipeline)
    lands bitwise on the pipelined run — overlap moves WALLS, never
    bytes;
  * ``efficiency_sane``           — the pipelined run reports an
    ``overlap_efficiency`` in [0, 1] (fraction of the segment wall
    NOT spent blocked in the drain stage);
  * ``two_slice_bitwise``         — the multislice leg: the plan
    re-planned for DeviceSpec(chips=2, slices=2) EXECUTES on the
    simulated hybrid mesh (the old ``dcn_slices > 1`` refusal is
    lifted; tiles fan out round-robin across slices with zero DCN
    bytes) and is bitwise the single-slice run;
  * ``coverage == 1.0``           — on the EVENTUAL-alive set (the
    churn convergence denominator, ops/nemesis.metric_alive);
  * ``measured <= predicted``     — the tile loop's AOT memory
    analysis lands inside the planner's predicted peak device bytes
    (the budget model's honesty gate, now including the third
    fetch-out staging buffer);

plus a crash-safety leg: the run is repeated with a halt after its
first checkpoint segment and resumed, and the resumed final state must
equal the uninterrupted one bitwise (the utils/checkpoint cursor
discipline through the streamed driver).

Everything lands in ONE run ledger (utils/telemetry — provenance first
line), so the committed artifact passes tools/validate_artifacts.py's
scale/plan/budget provenance gate.

    python tools/scale_capture.py [OUT.jsonl]    # default
        artifacts/ledger_scale_r23.jsonl
    python tools/scale_capture.py --smoke        # CPU rehearsal at
        2^14 nodes, .smoke-infixed artifact (hw_refresh convention)
    python tools/scale_capture.py --full-scale   # the 100M-node leg:
        plans against the DETECTED device topology and executes, into
        its own artifact (ledger_scale_full.jsonl — the structural
        record's run="last" readers must keep seeing a scale_record);
        refuses rc 1 off-TPU (real HBM only; rc 2 stays the hw_refresh
        wedge signature — ROADMAP item 3's hardware-capture remainder,
        run by the hw_refresh scale_plan step at the first healthy
        window)
    python tools/scale_capture.py --multislice   # the DETECTED-
        topology multislice executor leg: plans N = 2^20 against the
        real chip/HBM/slice topology and fans the tile stream across
        the reported DCN slices, into its own artifact
        (ledger_scale_multislice.jsonl).  Refuses rc 1 off-TPU or when
        detect_slices() < 2 — run by the hw_refresh scale_plan step
        when the structural record reports slices > 1.

Platform: ambient (the hw_refresh convention) — the committed record
on this container is the CPU structural proof; the same tool at a TPU
window measures real HBM numbers.
"""

import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N = 2**20
RUMORS = 256            # 8 word planes -> 4 tiles at the forced budget
FANOUT = 2
MAX_ROUNDS = 40
SEGMENT_EVERY = 10
SMOKE_N = 2**14
SMOKE_ROUNDS = 24
FULL_SCALE_N = 100_000_000


def mixed_fault(n):
    """The crashloop-style mixed program: crash/recover + permanent
    crash + open partition window + drop ramp, sized so coverage 1.0
    on the eventual-alive set is reachable inside MAX_ROUNDS."""
    from gossip_tpu.config import ChurnConfig, FaultConfig
    return FaultConfig(drop_prob=0.02, seed=2, churn=ChurnConfig(
        events=((3, 2, 8), (11, 3, -1)),
        partitions=((4, 10, n // 2),),
        ramp=(0, 6, 0.0, 0.15)))


def forced_plan(n, rounds, *, tiles_at_least=4):
    """Plan ``n`` against an HBM budget that forces >=
    ``tiles_at_least`` streamed tiles (the ONE shared construction,
    planner/budget.forced_device_for_tiles — the budget is recorded
    in the artifact; nothing about the trajectory depends on it)."""
    from gossip_tpu.planner import budget as PB
    fault = mixed_fault(n)
    dev = PB.forced_device_for_tiles(
        n, rumors=RUMORS, fanout=FANOUT, max_rounds=rounds,
        fault=fault, tiles_at_least=tiles_at_least)
    return PB.plan_scale(n, rumors=RUMORS, device=dev, fanout=FANOUT,
                         max_rounds=rounds, fault=fault,
                         segment_every=SEGMENT_EVERY)


def full_scale(led) -> int:
    """The 100M-node hardware leg: plan against the DETECTED topology
    and execute.  Gated on real TPU HBM — on any other backend this is
    a structural no-op refused rc 1 (rc 2 would read as the hw_refresh
    wedge signature; the hw_refresh step only passes --full-scale at a
    TPU window)."""
    import jax
    from gossip_tpu.planner import budget as PB
    from gossip_tpu.planner.stream import run_at_scale
    if jax.default_backend() != "tpu":
        # rc 1, not 2: off-TPU --full-scale is an operator error, and
        # rc 2 is the hw_refresh wedge-signature convention
        print(json.dumps({"error": "full-scale needs real TPU HBM",
                          "backend": jax.default_backend()}))
        return 1
    devs = jax.devices()
    stats = devs[0].memory_stats() or {}
    hbm = int(stats.get("bytes_limit", 16 * 1024**3))
    from gossip_tpu.parallel.multislice import detect_slices
    dev = PB.DeviceSpec(chips=len(devs), hbm_bytes_per_chip=hbm,
                        slices=detect_slices(devs))
    plan = PB.plan_scale(FULL_SCALE_N, rumors=64, device=dev,
                         fanout=FANOUT, max_rounds=64,
                         fault=mixed_fault(FULL_SCALE_N))
    led.event("scale_full_plan", **{
        "n": plan.n, "tiles": plan.tiles,
        "bucket_words": plan.bucket_words,
        "chips": dev.chips, "hbm_bytes_per_chip": hbm,
        "slices": dev.slices,
        "predicted_peak_device_bytes":
            plan.predicted_peak_device_bytes})
    res = run_at_scale(plan, measure_memory=True)
    led.event("scale_full_run", rounds=res.rounds,
              coverage=res.coverage, tiles=res.tiles,
              measured_loop_bytes=res.measured_loop_bytes)
    print(json.dumps({"full_scale": res.to_dict()}))
    return 0 if res.coverage == 1.0 else 1


def multislice_leg(led) -> int:
    """The detected-topology multislice executor leg: plan the
    structural N against the REAL chip/HBM/slice topology and fan the
    tile stream across the reported DCN slices (per-slice segments
    merging into the one host cursor, zero cross-slice bytes).  Gated
    on a real TPU backend reporting >= 2 slices — anywhere else this
    is an operator error refused rc 1 (rc 2 stays the hw_refresh
    wedge-signature convention; the hw_refresh step only passes
    --multislice when the structural record reports slices > 1)."""
    import jax
    from gossip_tpu.planner import budget as PB
    from gossip_tpu.planner.stream import run_at_scale
    if jax.default_backend() != "tpu":
        print(json.dumps({"error": "multislice leg needs real DCN "
                                   "slices",
                          "backend": jax.default_backend()}))
        return 1
    from gossip_tpu.parallel.multislice import detect_slices
    devs = jax.devices()
    slices = detect_slices(devs)
    if slices < 2:
        print(json.dumps({"error": "multislice leg needs >= 2 "
                                   "detected slices",
                          "slices": slices}))
        return 1
    stats = devs[0].memory_stats() or {}
    hbm = int(stats.get("bytes_limit", 16 * 1024**3))
    dev = PB.DeviceSpec(chips=len(devs), hbm_bytes_per_chip=hbm,
                        slices=slices)
    plan = PB.plan_scale(N, rumors=RUMORS, device=dev, fanout=FANOUT,
                         max_rounds=MAX_ROUNDS, fault=mixed_fault(N),
                         segment_every=SEGMENT_EVERY)
    res = run_at_scale(plan, check_bitwise=True, measure_memory=True)
    gates = {
        "executed_across_slices": res.dcn_slices == slices >= 2,
        "bitwise_equal": res.bitwise_equal is True,
        "coverage_1": res.coverage == 1.0,
    }
    ok = all(gates.values())
    led.event("scale_multislice_run", n=plan.n, tiles=res.tiles,
              chips=dev.chips, dcn_slices=res.dcn_slices,
              rounds=res.rounds, coverage=res.coverage,
              overlap_efficiency=res.overlap_efficiency,
              measured_loop_bytes=res.measured_loop_bytes,
              ok=ok, **gates)
    print(json.dumps({"multislice": res.to_dict(), "ok": ok,
                      "gates": gates}))
    return 0 if ok else 1


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    smoke = "--smoke" in argv
    full = "--full-scale" in argv
    multislice = "--multislice" in argv
    argv = [a for a in argv
            if a not in ("--smoke", "--full-scale", "--multislice")]
    infix = ".smoke" if smoke else ""
    # the full-scale and multislice legs get their OWN artifacts:
    # appending a run with no scale_record event to the structural
    # record would break its run="last" readers
    # (bench.last_scale_record, the tier-1 pin)
    if full:
        default_name = f"ledger_scale_full{infix}.jsonl"
    elif multislice:
        default_name = f"ledger_scale_multislice{infix}.jsonl"
    else:
        default_name = f"ledger_scale_r23{infix}.jsonl"
    out_path = (argv[0] if argv else
                os.path.join(REPO, "artifacts", default_name))
    if smoke:
        os.environ["JAX_PLATFORMS"] = "cpu"
    if not (full or multislice):
        # the structural record's two-slice leg needs >= 2 devices on
        # the default backend; off-TPU that means forcing the host
        # platform's device count BEFORE the first jax import (the
        # flag only touches the cpu platform, so it is inert at a real
        # TPU window)
        flags = os.environ.get("XLA_FLAGS", "")
        if "--xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=2"
            ).strip()
    n = SMOKE_N if smoke else N
    rounds = SMOKE_ROUNDS if smoke else MAX_ROUNDS

    import numpy as np

    import jax
    from gossip_tpu.parallel.multislice import detect_slices
    from gossip_tpu.planner import budget as PB
    from gossip_tpu.planner.stream import run_at_scale
    from gossip_tpu.utils import telemetry

    led = telemetry.Ledger(out_path)
    prev = telemetry.activate(led)
    try:
        led.record_runtime()
        if full:
            return full_scale(led)
        if multislice:
            return multislice_leg(led)
        plan = forced_plan(n, rounds)
        t0 = time.perf_counter()
        res = run_at_scale(plan, check_bitwise=True,
                           measure_memory=True, keep_state=True)
        streamed_ms = (time.perf_counter() - t0) * 1e3

        # A/B leg: the same plan with the pipeline OFF — every tile
        # drained the moment it is dispatched.  Overlap moves walls,
        # never bytes, so this must land bitwise on the pipelined run.
        t0 = time.perf_counter()
        r_ser = run_at_scale(plan, overlap=False, keep_state=True)
        serial_ms = (time.perf_counter() - t0) * 1e3
        no_overlap_bitwise = (
            np.array_equal(r_ser.final_state, res.final_state)
            and r_ser.msgs == res.msgs
            and r_ser.dropped == res.dropped)

        # multislice leg: re-plan the SAME trajectory for a simulated
        # 2-slice hybrid topology (chips=2, slices=2 — per_slice=1, so
        # each mesh row is one pinned device) and execute across it.
        # Tiles fan out round-robin with zero cross-slice bytes; the
        # slice count must be invisible to the result.
        dev2 = PB.DeviceSpec(
            chips=2, slices=2,
            hbm_bytes_per_chip=plan.device.hbm_bytes_per_chip,
            host_ram_bytes=plan.device.host_ram_bytes)
        plan2 = PB.plan_scale(plan.n, rumors=plan.rumors, device=dev2,
                              fanout=plan.fanout,
                              max_rounds=plan.max_rounds,
                              fault=plan.fault,
                              segment_every=plan.segment_every)
        r_2s = run_at_scale(plan2, keep_state=True)
        two_slice_bitwise = (
            plan2.mesh_kind == "hybrid" and r_2s.dcn_slices == 2
            and np.array_equal(r_2s.final_state, res.final_state)
            and r_2s.msgs == res.msgs
            and r_2s.dropped == res.dropped)

        # crash-safety leg: halt after the first published segment,
        # resume, and land bitwise on the uninterrupted run
        with tempfile.TemporaryDirectory() as td:
            ck = os.path.join(td, "scale_ck.npz")
            run_at_scale(plan, checkpoint_path=ck,
                         halt_after_segments=1)
            r2 = run_at_scale(plan, checkpoint_path=ck, resume=True,
                              keep_state=True)
        resume_bitwise = (np.array_equal(r2.final_state,
                                         res.final_state)
                          and r2.dropped == res.dropped
                          and r2.msgs == res.msgs)

        eff = res.overlap_efficiency
        gates = {
            "tiles_ge_4": res.tiles >= 4,
            "bitwise_equal": res.bitwise_equal is True,
            "no_overlap_bitwise": no_overlap_bitwise,
            "efficiency_sane": (eff is not None
                                and 0.0 <= eff <= 1.0),
            "two_slice_bitwise": two_slice_bitwise,
            "coverage_1": res.coverage == 1.0,
            "memory_within_prediction":
                res.measured_loop_bytes is not None
                and res.measured_loop_bytes
                <= res.predicted_peak_device_bytes,
            "resume_bitwise": resume_bitwise,
        }
        ok = all(gates.values())
        led.event("scale_record",
                  n=n, rumors=RUMORS, fanout=FANOUT, rounds=res.rounds,
                  tiles=res.tiles, bucket_words=res.bucket_words,
                  total_words=plan.total_words,
                  segments=res.segments_run,
                  backend=jax.default_backend(), smoke=smoke,
                  hbm_budget_bytes=plan.hbm_budget_bytes,
                  predicted_peak_device_bytes=
                  res.predicted_peak_device_bytes,
                  measured_loop_bytes=res.measured_loop_bytes,
                  coverage=res.coverage, msgs=res.msgs,
                  dropped=res.dropped,
                  streamed_wall_ms=round(streamed_ms, 1),
                  serial_wall_ms=round(serial_ms, 1),
                  overlap_efficiency=eff,
                  two_slice_tiles=r_2s.tiles,
                  two_slice_dcn_slices=r_2s.dcn_slices,
                  binding=plan.binding, ok=ok, **gates)
        print(json.dumps({"n": n, "tiles": res.tiles,
                          "coverage": res.coverage,
                          "measured_loop_bytes": res.measured_loop_bytes,
                          "predicted_peak_device_bytes":
                          res.predicted_peak_device_bytes,
                          "overlap_efficiency": eff,
                          "backend": jax.default_backend(),
                          "slices": detect_slices(),
                          "ok": ok, "gates": gates,
                          "ledger": out_path}))
        return 0 if ok else 1
    finally:
        telemetry.activate(prev)
        led.close()


if __name__ == "__main__":
    sys.exit(main())
