#!/usr/bin/env python
"""Crashloop: the paper's nemesis, pointed at the simulator itself.

The source harness crashes and partitions its *nodes* and checks that
gossip still converges (PAPER.md; Maelstrom's whole method).  This tool
applies the same discipline to OUR process: it launches a checkpointed
CLI run under a mixed fault program (crash/recover churn + a permanent
crash + a partition window + a drop ramp), SIGKILLs the process at K
randomized mid-segment points, resumes after each kill, and gates the
crash contract (utils/checkpoint module doc):

  * the final state is BITWISE equal to an uninterrupted run of the
    same config — every array, the message accounting, the absolute
    round cursor, and the exact destroyed-message total, no matter
    where the kills landed (inside an open partition window, mid-ramp);
  * coverage converges to 1.0 on the EVENTUAL alive set (the paper's
    convergence check, under our own process churn on top of the
    scheduled node churn);
  * the run ledger (utils/telemetry — provenance first line, one
    ``kill``/``resume`` event pair per cycle with the durable round
    cursor observed at the kill) parses per the flight-recorder
    contract; tools/validate_artifacts.py refuses any ``*crashloop*``
    artifact without provenance, so the committed record
    (artifacts/ledger_crashloop_r12.jsonl) can never be grandfathered.

Kill points are *round thresholds*: the harness polls the checkpoint's
durable round cursor and SIGKILLs the instant it crosses the next
threshold — i.e. while the NEXT compiled segment is in flight, so the
kill lands mid-segment by construction (a stranded ``path + ".tmp"``
partial, when the timing produces one, is recorded per kill and must be
cleaned by the next save).  Thresholds are drawn from ``--kill-seed``,
so a failing sequence replays exactly.

    python tools/crashloop.py                       # committed-record
        # config: n=16384 pushpull, 60 rounds, every=5, 3 kills ->
        # artifacts/ledger_crashloop_r12.jsonl
    python tools/crashloop.py --n 4096 --max-rounds 12 --every 4 \
        --kills 1 --poll-ms 2 --out /tmp/smoke.jsonl  # the tier-1 smoke

Runs on the hermetic CPU tier by design: the crash contract is a
bitwise-trajectory structure, not a chip rate.
"""

import argparse
import json
import os
import random
import signal
import subprocess
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DEFAULT_OUT = os.path.join(REPO, "artifacts",
                           "ledger_crashloop_r12.jsonl")

# hard deadline per child leg: a wedged child (e.g. a TPU tunnel
# handshake) must fail the harness loudly, never hang it
LEG_TIMEOUT_S = 600


def churn_flags(n: int, rounds: int):
    """The mixed fault program, scaled to the run: a crash/recover
    event, a permanent crash, a partition window long enough that a
    kill can land INSIDE it, and a drop ramp across the early segments
    — every schedule feature the SI engines honor, in one program."""
    heal = max(4, rounds // 2)
    return [
        "--churn-event", f"3:2:{heal}",
        "--churn-event", "7:3",                      # forever
        "--partition", f"{max(2, rounds // 6)}:{heal}:{n // 2}",
        "--drop-ramp", f"1:{max(3, rounds // 3)}:0.0:0.15",
    ]


def cli_argv(a, ckpt: str, resume: bool):
    argv = [sys.executable, "-m", "gossip_tpu", "run",
            "--mode", a.mode, "--n", str(a.n), "--fanout", "2",
            "--max-rounds", str(a.max_rounds), "--seed", str(a.seed),
            "--checkpoint", ckpt,
            "--checkpoint-every", str(a.every)]
    if a.devices > 1:
        argv += ["--devices", str(a.devices)]
    argv += churn_flags(a.n, a.max_rounds)
    if resume:
        argv.append("--resume")
    return argv


def durable_round(ckpt: str):
    """The checkpoint's absolute round cursor, or -1 before the first
    durable segment.  Atomic os.replace means a concurrent writer can
    never hand us a torn file.  Deliberately jax-free (np.load + json
    only): the poller's first call must not pay a multi-second jax
    import while the child is publishing segments."""
    try:
        with np.load(ckpt, allow_pickle=False) as z:
            meta = json.loads(str(z["__meta__"]))
        return int(meta.get("extra", {}).get("round", -1))
    except FileNotFoundError:
        return -1
    except Exception:
        return -1          # unreadable == no durable round yet


def run_to_completion(argv, env):
    p = subprocess.run(argv, capture_output=True, text=True, env=env,
                       timeout=LEG_TIMEOUT_S)
    if p.returncode != 0:
        raise RuntimeError(f"leg failed rc={p.returncode}:\n{p.stderr}")
    return json.loads(p.stdout)


def kill_at_round(argv, env, ckpt, threshold, max_rounds, log_prefix,
                  poll_s=0.01):
    """Launch the leg and SIGKILL it once the durable round cursor
    crosses ``threshold``.  Returns (killed: bool, observed_round,
    stale_tmp: bool, wall_s); killed=False means the leg completed —
    or published its FINAL checkpoint — before the threshold could be
    observed mid-run.  The final-cursor case matters: a SIGKILL after
    round ``max_rounds`` is durable would interrupt nothing, and a
    harness that counted it would certify crash recovery it never
    exercised (raise --n so segments outlast the poller instead).

    Child output goes to ``log_prefix``.out/.err FILES, not pipes — a
    chatty child filling an undrained pipe buffer would block mid-write
    and deadlock the poll loop."""
    t0 = time.perf_counter()
    with open(log_prefix + ".out", "wb") as fo, \
            open(log_prefix + ".err", "wb") as fe:
        proc = subprocess.Popen(argv, stdout=fo, stderr=fe, env=env)
        try:
            while True:
                rc = proc.poll()
                r = durable_round(ckpt)
                if rc is not None:
                    if rc != 0:
                        err = open(log_prefix + ".err",
                                   errors="replace").read()
                        raise RuntimeError(
                            f"leg died on its own rc={rc}:\n{err}")
                    return False, r, False, time.perf_counter() - t0
                if time.perf_counter() - t0 > LEG_TIMEOUT_S:
                    raise RuntimeError(
                        f"leg exceeded {LEG_TIMEOUT_S}s without "
                        f"reaching round {threshold} (wedged child?)")
                if r >= max_rounds:
                    # all work is already durable: a kill now is
                    # vacuous — let the leg finish and report
                    # completed_before_kill
                    proc.wait()
                    return False, r, False, time.perf_counter() - t0
                if r >= threshold:
                    proc.send_signal(signal.SIGKILL)
                    proc.wait()
                    stale = os.path.exists(ckpt + ".tmp")
                    return True, r, stale, time.perf_counter() - t0
                time.sleep(poll_s)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait()


def assert_bitwise_equal(ref_ckpt: str, crash_ckpt: str):
    """Every array and the whole metadata entry (config fingerprint,
    absolute round, exact dropped total) must match bitwise."""
    problems = []
    with np.load(ref_ckpt, allow_pickle=False) as a, \
            np.load(crash_ckpt, allow_pickle=False) as b:
        if sorted(a.files) != sorted(b.files):
            return [f"entry sets differ: {sorted(a.files)} vs "
                    f"{sorted(b.files)}"]
        for name in a.files:
            if name == "__meta__":
                ma, mb = (json.loads(str(a[name])),
                          json.loads(str(b[name])))
                if ma != mb:
                    problems.append(f"metadata differs: {ma} vs {mb}")
            elif not np.array_equal(np.asarray(a[name]),
                                    np.asarray(b[name])):
                problems.append(f"array {name!r} differs")
    return problems


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--n", type=int, default=16384,
                    help="node count; the default is big enough that a "
                         "segment outlasts the kill poller on CPU — a "
                         "tiny n can outrun it and complete early")
    ap.add_argument("--mode", default="pushpull")
    ap.add_argument("--max-rounds", type=int, default=60)
    ap.add_argument("--every", type=int, default=5)
    ap.add_argument("--kills", type=int, default=3)
    ap.add_argument("--devices", type=int, default=1)
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--kill-seed", type=int, default=12,
                    help="seeds the randomized kill thresholds (a "
                         "failing sequence replays exactly)")
    ap.add_argument("--poll-ms", type=float, default=10.0,
                    help="cursor poll interval; must be well under the "
                         "per-segment wall or the child publishes its "
                         "final checkpoint between polls and the kill "
                         "is refused as vacuous (smoke configs: ~4k "
                         "nodes with --poll-ms 2)")
    ap.add_argument("--workdir", default=None,
                    help="checkpoint scratch dir (default: a fresh "
                         "temp dir)")
    ap.add_argument("--out", default=DEFAULT_OUT)
    a = ap.parse_args(argv)

    if a.workdir is None:
        import tempfile
        a.workdir = tempfile.mkdtemp(prefix="crashloop_")
    os.makedirs(a.workdir, exist_ok=True)
    ref_ckpt = os.path.join(a.workdir, "reference.npz")
    crash_ckpt = os.path.join(a.workdir, "crashloop.npz")
    for p in (ref_ckpt, crash_ckpt, crash_ckpt + ".tmp"):
        if os.path.exists(p):
            os.remove(p)

    # children inherit the caller's platform pins (the tier-1 smoke
    # passes JAX_PLATFORMS=cpu + the session compile cache); the
    # harness itself never imports jax — np.load + json reads only
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    # children run `-m gossip_tpu`; make the repo importable no matter
    # where the harness was launched from
    env["PYTHONPATH"] = REPO + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")

    from gossip_tpu.utils import telemetry
    led = telemetry.Ledger(a.out)
    prov = {"run_id": led.run_id}
    rng = random.Random(a.kill_seed)
    # thresholds stay below the LAST segment's start: a threshold past
    # max_rounds - every could only fire on the final checkpoint, when
    # there is no mid-segment work left to kill
    lo, hi = a.every, max(a.every + 1, a.max_rounds - a.every)
    # one randomized threshold per equal slice of the round budget:
    # kills SPREAD across the run (early segment, inside the partition
    # window, late) instead of clustering wherever one draw lands
    pool = []
    for i in range(a.kills):
        s0 = lo + (hi - lo) * i // a.kills
        s1 = max(s0 + 1, lo + (hi - lo) * (i + 1) // a.kills)
        pool.append(rng.randrange(s0, s1))
    pool.sort()
    led.event("config", n=a.n, mode=a.mode, max_rounds=a.max_rounds,
              every=a.every, kills=a.kills, devices=a.devices,
              seed=a.seed, kill_seed=a.kill_seed,
              kill_thresholds=pool,
              churn=churn_flags(a.n, a.max_rounds))

    # ---- reference leg: the uninterrupted run -----------------------
    t0 = time.perf_counter()
    ref = run_to_completion(cli_argv(a, ref_ckpt, resume=False), env)
    led.event("reference_done", wall_s=round(time.perf_counter() - t0, 3),
              coverage=ref["coverage"], rounds=ref["rounds"],
              dropped=ref.get("dropped"),
              fault_program=ref.get("fault_program"))

    # ---- crash leg: run / SIGKILL / resume, K times -----------------
    kills_done = 0
    kill_rounds = []
    final = None
    resume = False
    for threshold in pool:
        # each leg must publish at least one NEW durable segment before
        # its kill — a threshold the cursor already crossed would kill
        # the resume before it did any work, proving nothing
        threshold = max(threshold, durable_round(crash_ckpt) + 1)
        killed, at, stale, wall = kill_at_round(
            cli_argv(a, crash_ckpt, resume=resume), env, crash_ckpt,
            threshold, a.max_rounds,
            os.path.join(a.workdir, f"leg{kills_done + 1}"),
            poll_s=a.poll_ms / 1000.0)
        if not killed:
            # the leg outran the poller and completed; the remaining
            # kills have nothing to kill — record honestly and stop
            led.event("completed_before_kill", threshold=threshold,
                      durable_round=at, wall_s=round(wall, 3))
            break
        kills_done += 1
        kill_rounds.append(at)
        # provenance AT the kill point: the durable cursor the next
        # resume will continue from, stamped with this run's identity
        led.event("kill", seq=kills_done, threshold=threshold,
                  durable_round=at, stale_tmp=stale,
                  wall_s=round(wall, 3), **prov)
        resume = True
    if resume:
        t0 = time.perf_counter()
        final = run_to_completion(cli_argv(a, crash_ckpt, resume=True),
                                  env)
        led.event("resume_done", resumed_from=durable_round(crash_ckpt),
                  wall_s=round(time.perf_counter() - t0, 3),
                  coverage=final["coverage"], dropped=final.get("dropped"))
    else:
        final = run_to_completion(cli_argv(a, crash_ckpt, resume=False),
                                  env)

    # ---- verdict ----------------------------------------------------
    problems = assert_bitwise_equal(ref_ckpt, crash_ckpt)
    if kills_done < a.kills:
        problems.append(f"only {kills_done}/{a.kills} kills landed "
                        "(raise --max-rounds or lower --every)")
    if any(k >= a.max_rounds for k in kill_rounds):
        # belt-and-braces twin of the kill_at_round guard: no recorded
        # kill may postdate the final durable state
        problems.append("a kill landed after the final checkpoint "
                        f"(durable rounds {kill_rounds}) — it "
                        "interrupted nothing")
    if final["coverage"] != 1.0:
        problems.append("crashloop leg did not converge on the "
                        f"eventual-alive set: coverage={final['coverage']}")
    if ref["coverage"] != 1.0:
        problems.append("reference leg did not converge: "
                        f"coverage={ref['coverage']}")
    for key in ("coverage", "msgs", "rounds", "dropped",
                "fault_program"):
        if ref.get(key) != final.get(key):
            problems.append(f"report {key!r} differs: {ref.get(key)} "
                            f"vs {final.get(key)}")
    led.event("verdict", ok=not problems, kills=kills_done,
              bitwise_equal=not [p for p in problems if "differ" in p],
              coverage=final["coverage"], dropped=final.get("dropped"),
              problems=problems)
    led.close()
    if problems:
        for p in problems:
            print(f"CRASHLOOP FAIL: {p}", file=sys.stderr)
        return 1
    print(json.dumps({"ok": True, "kills": kills_done,
                      "coverage": final["coverage"],
                      "dropped": final.get("dropped"),
                      "ledger": a.out}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
