#!/usr/bin/env python
"""Capture the compile-amortized churn-sweep record (the traced-operand
PR's acceptance artifact).

Two legs over the SAME K nemesis scenarios on the dense sharded driver
(parallel/sharded.simulate_curve_sharded):

  * ``solo`` — K reruns, each forced through a fresh trace + XLA
    compile (the shape-keyed loop memo and jax's in-memory caches are
    cleared between scenarios, and the persistent compile cache is
    suspended) — the pre-PR cost model, where every ChurnConfig baked
    its schedule into the program and no cache could serve a sibling
    scenario;
  * ``warm`` — the same K scenarios through the ONE memoized compiled
    loop (schedules as runtime operands): scenario 1 pays the only
    compile (reported separately as ``compile_ms``), scenarios 2..K are
    in-memory executable reuses.  The acceptance line is
    ``solo_total_ms >= 3 * warm_total_ms``.

A third leg runs the scenario-BATCHED sweep
(parallel/sweep.churn_sweep_curves): all K scenarios as one vmapped XLA
program, with per-scenario summaries (convergence, exact dropped
totals) ledgered as ``churn_sweep_scenario`` events.

Everything lands in ONE run ledger (utils/telemetry — provenance first
line, per-scenario ``round_metrics`` events with the nemesis columns
flushed by the drivers themselves), so the committed artifact passes
tools/validate_artifacts.py's churn-artifact provenance gate.

    python tools/churn_sweep_capture.py [OUT.jsonl]   # default
        artifacts/ledger_churn_sweep_r11.jsonl

Runs on the hermetic CPU tier by design (the amortization ratio is a
compile-vs-reuse structure, not a chip rate; the TPU rate story lives
in BENCH/hw_refresh).
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

K = 8
N = 64 * 4
DEVICES = 4
MAX_ROUNDS = 16


def scenarios():
    """K mixed fault programs — the ONE shared scenario-family
    generator (ops/nemesis.mixed_scenarios; the dry-run churn_sweep
    family and bench.py's families leg draw from it too)."""
    from gossip_tpu.ops import nemesis as NE
    return NE.mixed_scenarios(K, N, drop_prob=0.02, seed=2)


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    out_path = (argv[0] if argv else
                os.path.join(REPO, "artifacts",
                             "ledger_churn_sweep_r11.jsonl"))
    # hermetic: the persistent/AOT cache must not serve the solo leg
    os.environ["GOSSIP_COMPILE_CACHE"] = ""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={DEVICES}"
        ).strip()

    import jax
    import numpy as np
    from gossip_tpu import config as C
    from gossip_tpu.config import ProtocolConfig, RunConfig
    from gossip_tpu.parallel import sharded
    from gossip_tpu.parallel.sweep import churn_sweep_curves
    from gossip_tpu.topology import generators as G
    from gossip_tpu.utils import telemetry

    topo = G.complete(N)
    proto = ProtocolConfig(mode=C.PUSH_PULL, fanout=2, rumors=2)
    run = RunConfig(seed=0, max_rounds=MAX_ROUNDS, target_coverage=1.0)
    mesh = sharded.make_mesh(DEVICES)
    faults = scenarios()

    led = telemetry.Ledger(out_path)
    prev = telemetry.activate(led)
    try:
        led.record_runtime()

        def one(fault):
            t0 = time.perf_counter()
            covs, msgs, _ = sharded.simulate_curve_sharded(
                proto, topo, run, mesh, fault)
            return (time.perf_counter() - t0) * 1e3, covs, msgs

        # -- solo leg: every scenario pays trace + compile ------------
        solo_ms = []
        for i, f in enumerate(faults):
            sharded._cached_dense_loop.cache_clear()
            jax.clear_caches()
            ms, covs, _ = one(f)
            solo_ms.append(ms)
            led.event("churn_sweep_solo", scenario=i,
                      wall_ms=round(ms, 1),
                      final_coverage=round(float(covs[-1]), 6))

        # -- warm leg: one compile, K reuses --------------------------
        sharded._cached_dense_loop.cache_clear()
        jax.clear_caches()
        t0 = time.perf_counter()
        one(faults[0])                      # the only compile
        compile_ms = (time.perf_counter() - t0) * 1e3
        warm_ms = []
        for i, f in enumerate(faults):
            ms, covs, _ = one(f)
            warm_ms.append(ms)
            led.event("churn_sweep_warm", scenario=i,
                      wall_ms=round(ms, 1),
                      final_coverage=round(float(covs[-1]), 6))

        solo_total, warm_total = sum(solo_ms), sum(warm_ms)
        speedup = solo_total / max(warm_total, 1e-9)

        # -- batched leg: all K as one vmapped program ----------------
        t0 = time.perf_counter()
        res = churn_sweep_curves(proto, topo, run, faults)
        batched_first_ms = (time.perf_counter() - t0) * 1e3
        t0 = time.perf_counter()
        res = churn_sweep_curves(proto, topo, run, faults)
        batched_warm_ms = (time.perf_counter() - t0) * 1e3
        for i, s in enumerate(res.summaries()):
            led.event("churn_sweep_scenario", idx=i, **s)

        led.event("churn_sweep_record",
                  k=K, n=N, devices=DEVICES, driver="dense_sharded",
                  max_rounds=MAX_ROUNDS,
                  solo_total_ms=round(solo_total, 1),
                  warm_total_ms=round(warm_total, 1),
                  compile_ms=round(compile_ms, 1),
                  speedup=round(speedup, 2),
                  batched_first_ms=round(batched_first_ms, 1),
                  batched_warm_ms=round(batched_warm_ms, 1),
                  accept_3x=bool(solo_total >= 3 * warm_total))
        line = {"k": K, "solo_total_ms": round(solo_total, 1),
                "warm_total_ms": round(warm_total, 1),
                "speedup": round(speedup, 2),
                "batched_warm_ms": round(batched_warm_ms, 1),
                "ledger": out_path}
        print(json.dumps(line))
        return 0 if solo_total >= 3 * warm_total else 1
    finally:
        telemetry.activate(prev)
        led.close()


if __name__ == "__main__":
    sys.exit(main())
