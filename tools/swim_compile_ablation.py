#!/usr/bin/env python
"""Ablate the SWIM step's compile time on the real chip.

The r04 capture decomposed SWIM-1M's wall into ~120 s of XLA compile
(sort lowering) + ~12-16 s steady (docs/PERF.md "SWIM-1M cost budget"),
making compile the dominant cost of the whole BASELINE row.  This
experiment answers *what* XLA spends that time on, by AOT-lowering and
compiling the 1M-node step with each major component stubbed out in
turn (the stubs keep all shapes/dtypes so the rest of the program is
unchanged):

  full       the real step (sort dissemination default)
  no_probe   probe_draws -> constant zeros (kills the 1M-lane threefry
             probe/proxy draw chain: 5 fold_in+randint streams)
  no_diss    disseminate_max -> zeros (kills sort + segment-max)
  no_sample  sample_peers -> ring targets (kills the table gather +
             per-node partner threefry)
  scatter    swim_diss='scatter' control (the pre-r04 lowering)
  barrier_alive
             base_alive wrapped in lax.optimization_barrier — tests
             whether XLA's interpreted constant-folding of the 1M-bool
             liveness subgraph (and everything folded through it) is
             the residual ~120 s (first run's verdict: no_probe /
             no_diss / no_sample each save only ~3 s, so the hog is
             none of the three data-movement components)

Each variant reports trace+lower seconds and backend compile seconds
for the BARE step (the sweep row additionally compiles the early-exit
until-driver around it, so absolute numbers here sit below the row's
compile_s; the *deltas* are the signal).  Writes one JSON line per
variant and artifacts/swim_compile_ablation_r04.json.

Run only when the tunnel is healthy (tools/tunnel_watchdog.py probes).
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ART = os.path.join(REPO, "artifacts", "swim_compile_ablation_r04.json")

N = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
PROTO_KW = dict(mode="swim", fanout=2, swim_proxies=3, swim_subjects=8,
                swim_suspect_rounds=24)


def main():
    sys.path.insert(0, REPO)
    import jax
    import jax.numpy as jnp
    from gossip_tpu.config import ProtocolConfig, TopologyConfig
    from gossip_tpu import topology
    from gossip_tpu.models import swim as SW

    print(f"devices: {jax.devices()}", file=sys.stderr)
    topo = topology.build(TopologyConfig(family="power_law", n=N, k=3,
                                         degree_cap=256))
    real_probe = SW.probe_draws
    real_diss = SW.disseminate_max
    real_sample = SW.sample_peers
    real_alive = SW.base_alive

    def barrier_alive(n, dead_nodes, fault):
        return jax.lax.optimization_barrier(
            real_alive(n, dead_nodes, fault))

    def stub_probe(rkey, gids, s_count, n, proxies, drop_prob):
        m = len(gids)
        return (jnp.zeros((m,), jnp.int32), jnp.zeros((m,), jnp.bool_),
                jnp.zeros((m, proxies), jnp.int32),
                jnp.zeros((m, proxies), jnp.bool_),
                jnp.zeros((m, proxies), jnp.bool_))

    def stub_diss(targets, wire, num_rows, impl="sort", max_rounds=None):
        return jnp.zeros((num_rows, wire.shape[1]), jnp.int32)

    def stub_sample(key, ids, topo_, fanout, exclude_self=True,
                    local_nbrs=None, local_deg=None):
        ring = (ids[:, None] + 1 + jnp.arange(fanout)[None, :]) % N
        return ring.astype(jnp.int32)

    variants = [
        ("full", "sort", {}),
        ("no_probe", "sort", {"probe_draws": stub_probe}),
        ("no_diss", "sort", {"disseminate_max": stub_diss}),
        ("no_sample", "sort", {"sample_peers": stub_sample}),
        ("scatter", "scatter", {}),
        ("barrier_alive", "sort", {"base_alive": barrier_alive}),
    ]
    if len(sys.argv) > 2:      # run a named subset, e.g. barrier_alive
        want = set(sys.argv[2:])
        variants = [v for v in variants if v[0] in want or v[0] == "full"]
    rows = []
    for name, impl, patches in variants:
        proto = ProtocolConfig(swim_diss=impl, **PROTO_KW)
        for attr, fn in patches.items():
            setattr(SW, attr, fn)
        try:
            step, tables = SW.make_swim_round(proto, N, dead_nodes=(1,),
                                              fail_round=2, topo=topo,
                                              tabled=True)
            st = SW.init_swim_state(N, proto.swim_subjects, seed=0)
            t0 = time.time()
            lowered = jax.jit(step).lower(st, *tables)
            t1 = time.time()
            lowered.compile()
            t2 = time.time()
            row = {"variant": name, "lower_s": round(t1 - t0, 2),
                   "compile_s": round(t2 - t1, 2)}
        finally:
            SW.probe_draws = real_probe
            SW.disseminate_max = real_diss
            SW.sample_peers = real_sample
            SW.base_alive = real_alive
        print(json.dumps(row), flush=True)
        rows.append(row)

    full = next(r for r in rows if r["variant"] == "full")
    for r in rows:
        r["delta_vs_full_s"] = round(r["compile_s"] - full["compile_s"], 2)
    prior = {}
    if os.path.exists(ART):
        with open(ART) as f:
            prior = json.load(f)
    if N == 1_000_000:
        # subset runs merge into earlier rows
        merged = {r["variant"]: r for r in prior.get("rows", [])}
        merged.update({r["variant"]: r for r in rows})
        # deltas must all be relative to the full row IN THIS FILE —
        # a subset merge replaces "full", so recompute every delta
        full_c = merged["full"]["compile_s"]
        for r in merged.values():
            r["delta_vs_full_s"] = round(r["compile_s"] - full_c, 2)
        prior.update({"n": N, "proto": PROTO_KW,
                      "note": __doc__.split("\n")[0],
                      "rows": list(merged.values())})
    elif prior:
        # non-1M full runs feed the compile-vs-n scaling curve instead
        # of the ablation rows (and never clobber them)
        scaling = prior.setdefault("scaling_compile_s_by_n", {})
        scaling[str(N)] = full["compile_s"]
    else:
        return 0    # CPU smoke before any 1M artifact exists: no write
    # stamped per write: the merged artifact's attribution is the run
    # that last touched it (the one artifact schema —
    # tools/validate_artifacts.py / staticcheck writer gate)
    from _telemetry import telemetry
    prior["provenance"] = telemetry().provenance()
    with open(ART, "w") as f:
        json.dump(prior, f, indent=1)
    print(f"wrote {ART}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
