#!/usr/bin/env python
"""Re-measure docs/PERF.md's interactive-provenance kernel numbers into a
committed artifact (VERDICT r4 task 1b).

docs/PERF.md "Kernel-level numbers" still carries four round-1/2
interactive-session measurements no committed artifact records: the
fused single-rumor ms/round at 10M, the VMEM-OOM ladder that justified
the staged big-MR split, the device-side topology-build speedup, and
(from the round-5 candidates list) the fused fault-mask on-cost.  This
tool re-measures all of them in one session and writes
artifacts/kernel_numbers_r05.json:

  1. fused single-rumor round at N=10M: ms/round (the "~3 ms" bullet)
  2. VMEM OOM ladder: the 10M x 32-rumor VALUE kernel force-compiled
     (bypassing the staged-path routing) so XLA's own VMEM-exceeded
     message — with its MiB figure — lands in the artifact (the
     "152.7 MiB vs 128 MiB" bullet)
  3. 1M-node power_law (cap 256) topology build, end-to-end device
     seconds (the "110 s -> 21 s" bullet)
  4. fault-mask on-cost at the 10M flagship shape: ms/round with
     masks off vs drop_prob=0.05 + 1% dead nodes in-kernel (designed
     ~zero off / one VMEM AND per pull on — round-5 candidate #3)
  5. the staged big-MR path at fanout 2 (round-5 multi-pass
     accumulation) timed at the flagship shape — VERDICT r4 task 8's
     "route works at 10M x 32 fanout=2" as a measured row

Reference for the hot loop all of these serve: /root/reference/
main.go:72-88 (semantics contract; the numbers are ours).

Run at a healthy tunnel window.  ``--smoke`` rehearses on the CPU
interpreter at tiny shapes (.smoke artifact, repo convention).
"""

import argparse
import functools
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
try:
    from _timing import timed_chain as _timed_chain  # noqa: E402
finally:
    sys.path.pop(0)


def _time_rounds(step, init_table, rounds: int) -> float:
    """ms/round (shared scaffold: tools/_timing.timed_chain, seconds)."""
    return _timed_chain(step, init_table, rounds) * 1e3


def single_rumor_ms(n: int, interpret: bool, rounds: int) -> dict:
    from gossip_tpu.ops.pallas_round import (fused_pull_round,
                                             init_fused_state)
    st = init_fused_state(n)
    ms = _time_rounds(
        lambda i, t: fused_pull_round(t, 0, i, n, 1, interpret),
        st.table, rounds)
    return {"n": n, "ms_per_round": round(ms, 4),
            "node_rounds_per_s": round(n / ms * 1e3, 1)}


def vmem_oom_ladder(n: int, rumors: int, interpret: bool) -> dict:
    """Force the whole-table VALUE kernel at a shape the router sends to
    the staged path, so the XLA VMEM-exceeded message (with its MiB
    requirement) is captured verbatim.  In smoke/interpreter mode there
    is no VMEM to exceed — the rehearsal just proves the bypass plumbing
    compiles."""
    import jax
    import jax.numpy as jnp

    from gossip_tpu.ops import pallas_round as PR

    rows = PR.mr_rows(n)
    table_bytes = rows * PR.LANES * 4
    kernel = functools.partial(PR._fused_mr_kernel, rows=rows, fanout=1,
                               n=n, inject=False)

    def forced_round(table):
        return PR._fused_call(kernel, rows, jnp.int32(0), jnp.int32(1),
                              table, None, interpret, round_salt=0x5D0)

    spec = jax.ShapeDtypeStruct((rows, PR.LANES), jnp.uint32)
    out = {"n": n, "rumors": rumors, "rows": rows,
           "table_mib": round(table_bytes / 2**20, 2),
           "routed_to_staged": PR._mr_wants_big(table_bytes, 1)}
    try:
        jax.jit(forced_round).lower(spec).compile()
        out["value_kernel_compiles"] = True
    except Exception as e:
        msg = str(e)
        out["value_kernel_compiles"] = False
        # keep the juicy part: XLA prints the VMEM requirement in MiB
        idx = msg.lower().find("vmem")
        out["oom_message"] = msg[max(0, idx - 200):idx + 500] or msg[:700]
    return out


def mr_staged_fanout2_ms(n: int, rumors: int, interpret: bool,
                         rounds: int) -> dict:
    """Per-round ms of the staged big-MR path at fanout 2 (round-5
    multi-pass accumulation — VERDICT r4 task 8 wants the route proven
    at the flagship 10M x 32 shape; expected ~2x the fanout-1 HBM
    cost)."""
    from gossip_tpu.ops import pallas_round as PR
    st = PR.init_multirumor_state(n, rumors)
    # call the staged path DIRECTLY: at smoke scale the public router
    # would pick the value kernel and the artifact row would mislabel
    # which code path produced the number
    ms = _time_rounds(
        lambda i, t: PR._fused_mr_round_big(t, 0, i, n, interpret, None,
                                            fanout=2),
        st.table, rounds)
    return {"n": n, "rumors": rumors, "fanout": 2, "path": "staged_big",
            "ms_per_round": round(ms, 4)}


def topology_build_s(n: int) -> dict:
    from gossip_tpu.config import TopologyConfig
    from gossip_tpu.topology import generators as G
    import jax
    tc = TopologyConfig(family="power_law", n=n, k=3, degree_cap=256)
    t0 = time.perf_counter()
    topo = G.build(tc)
    jax.block_until_ready((topo.nbrs, topo.deg))
    wall = time.perf_counter() - t0
    return {"n": n, "family": "power_law", "degree_cap": 256,
            "build_s": round(wall, 2),
            "table_shape": list(topo.nbrs.shape)}


def fault_mask_cost(n: int, interpret: bool, rounds: int) -> dict:
    from gossip_tpu.config import FaultConfig
    from gossip_tpu.ops.pallas_round import (fault_masks_node_packed,
                                             fused_pull_round,
                                             init_fused_state)
    st = init_fused_state(n)
    off_ms = _time_rounds(
        lambda i, t: fused_pull_round(t, 0, i, n, 1, interpret),
        st.table, rounds)
    fault = FaultConfig(node_death_rate=0.01, drop_prob=0.05, seed=0)
    alive_table, thresh = fault_masks_node_packed(fault, n)
    on_ms = _time_rounds(
        lambda i, t: fused_pull_round(t, 0, i, n, 1, interpret,
                                      drop_threshold=thresh,
                                      alive_table=alive_table),
        st.table, rounds)
    return {"n": n, "masks_off_ms_per_round": round(off_ms, 4),
            "masks_on_ms_per_round": round(on_ms, 4),
            "on_cost_pct": round((on_ms / off_ms - 1) * 100, 1)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=10_000_000)
    ap.add_argument("--topo-n", type=int, default=1_000_000)
    ap.add_argument("--rounds", type=int, default=20)
    ap.add_argument("--smoke", action="store_true")
    a = ap.parse_args()
    smoke = a.smoke
    if smoke:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        n, topo_n, rounds = 4096 * 8, 20_000, 2
    else:
        n, topo_n, rounds = a.n, a.topo_n, a.rounds

    import jax
    backend = jax.default_backend()
    from gossip_tpu.utils import telemetry
    doc = {"what": ("re-measurement of docs/PERF.md's interactive-"
                    "provenance kernel numbers (VERDICT r4 1b); see "
                    "module doc for the four items"),
           # the one artifact schema (tools/validate_artifacts.py):
           # regenerations must be attributable even though the
           # committed file is legacy-allowlisted by name
           # (staticcheck artifact-writer-provenance gate)
           "provenance": telemetry.provenance(),
           "backend": backend, "smoke": smoke}
    doc["single_rumor"] = single_rumor_ms(n, smoke, rounds)
    doc["mr_staged_fanout2"] = mr_staged_fanout2_ms(n, 32, smoke, rounds)
    doc["vmem_oom_ladder"] = vmem_oom_ladder(n, 32, smoke)
    doc["topology_build"] = topology_build_s(topo_n)
    doc["fault_mask"] = fault_mask_cost(n, smoke, rounds)

    infix = ".smoke" if smoke else ""
    art = os.path.join(REPO, "artifacts", f"kernel_numbers_r05{infix}.json")
    with open(art, "w") as f:
        json.dump(doc, f, indent=1)
    print(json.dumps({"single_ms": doc["single_rumor"]["ms_per_round"],
                      "mr_fanout2_ms": doc["mr_staged_fanout2"]
                      ["ms_per_round"],
                      "oom_captured": not doc["vmem_oom_ladder"]
                      .get("value_kernel_compiles", True),
                      "topo_build_s": doc["topology_build"]["build_s"],
                      "fault_on_cost_pct": doc["fault_mask"]["on_cost_pct"],
                      "backend": backend, "smoke": smoke}))
    print(f"wrote {art}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
