#!/usr/bin/env python
"""Tunnel watchdog: probe the axon TPU tunnel on a timer and fire the
hardware refresh at the FIRST healthy window.

The single-client axon tunnel wedges for an hour or more when a TPU
process dies mid-operation, and a wedged tunnel hangs ANY jax init —
so hardware capture can't be an end-of-round step; it has to pounce on
whatever healthy window appears during the round.  This script:

  1. probes ``jax.devices()`` in a subprocess (120 s timeout — a healthy
     tunnel answers in seconds; a timeout is the wedge signature),
  2. ledgers one event per probe to
     artifacts/ledger_tunnel_watchdog.jsonl (utils/telemetry schema;
     render with tools/telemetry_report.py),
  3. on the first success, immediately runs tools/hw_refresh.py under
     its own worst-case budget, tee-ing output to
     artifacts/hw_refresh_r05.log, then exits.

Probe spacing (default 480 s since round 5 — VERDICT r4 flagged the
old 1200 s default's up-to-22-min detection latency after the only r04
window lasted ~11 min) trades against the fact that killing a
timed-out probe itself leaves a dead TPU-client process, which can
prolong a wedge — the same trade bench.py's retry loop makes, now
tilted toward catching short windows.  Only the wedge signature (timeout) is retried;
three consecutive FAST probe failures (broken install / plugin import
error) are deterministic, so the watchdog gives up rather than burn
the round probing a dead configuration.

    nohup python tools/tunnel_watchdog.py --max-hours 10 &
"""

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HEALTH_LOG = os.path.join(REPO, "artifacts", "ledger_tunnel_watchdog.jsonl")
REFRESH_LOG = os.path.join(REPO, "artifacts", "hw_refresh_r05.log")
PROBE_TIMEOUT_S = 120

_LEDGER = None


def _ledger():
    """The watchdog's health log IS a run ledger since round 7
    (utils/telemetry schema: provenance line, run ids, fsync per
    event) — the hand-rolled r04/r05 tunnel_health JSONLs were the
    only evidence the dark rounds left, and they carried no
    provenance, so probe timelines could not be mechanically joined
    with the refresh artifacts they gated.  Render / join with
    tools/telemetry_report.py."""
    global _LEDGER
    if _LEDGER is None:
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        try:
            from _telemetry import open_ledger
        finally:
            sys.path.pop(0)
        _LEDGER = open_ledger(HEALTH_LOG)
    return _LEDGER


def log_line(obj):
    """One durable ledger event (kind = the line's ``event`` field),
    still echoed to stdout for the operator's nohup log."""
    obj = dict(obj)
    kind = obj.pop("event", "note")
    _ledger().event(kind, **obj)
    print(json.dumps({"event": kind, **obj}), flush=True)


def probe():
    """(ok, detail).  detail is 'timeout' for the wedge signature,
    'fast-fail' for a deterministic init error, or the device list."""
    t0 = time.time()
    try:
        p = subprocess.run(
            [sys.executable, "-c", "import jax; print(jax.devices())"],
            capture_output=True, text=True, timeout=PROBE_TIMEOUT_S)
    except subprocess.TimeoutExpired:
        return False, "timeout", round(time.time() - t0, 1)
    wall = round(time.time() - t0, 1)
    if p.returncode != 0:
        return False, "fast-fail: " + (p.stderr or "")[-200:], wall
    return True, p.stdout.strip()[-200:], wall


def run_cmd(cmd, budget_s):
    """An arbitrary capture command at a healthy window, same contract
    as :func:`run_refresh`: own process group, whole group killed on
    budget overrun (a half-killed TPU client wedges the single-client
    tunnel for everyone after us), output appended to the refresh
    log."""
    import signal
    log_line({"event": "cmd_start", "cmd": cmd, "budget_s": budget_s})
    with open(REFRESH_LOG, "a") as f:
        f.write(f"\n=== cmd at {time.strftime('%Y-%m-%dT%H:%M:%S')}: "
                f"{cmd} ===\n")
        f.flush()
        p = subprocess.Popen(cmd, shell=True, stdout=f,
                             stderr=subprocess.STDOUT, cwd=REPO,
                             start_new_session=True)
        try:
            rc = p.wait(timeout=budget_s)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(os.getpgid(p.pid), signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
            p.wait()
            rc = "timeout"
    log_line({"event": "cmd_done", "rc": rc})
    return rc


def run_refresh():
    """hw_refresh (pending steps only) under its worst-case budget.

    Returns hw_refresh's exit code (0 every pending step went green /
    1 partial / 2 nothing / "timeout").  Retries are incremental:
    hw_refresh merges its per-step summary across runs, so only the
    steps without a green line are re-run — a captured headline from an
    earlier window is never re-burned or clobbered.  The child runs in
    its own process group and the WHOLE group is killed on timeout:
    hw_refresh's steps are grandchild subprocesses holding the
    single-client tunnel, and killing only the middle process would
    leave an unsupervised TPU client wedging it for everyone after
    us."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import signal

    import hw_refresh
    pending = hw_refresh.pending_steps()
    if not pending:
        log_line({"event": "hw_refresh_skip",
                  "reason": "summary already fully green"})
        return 0
    budget = hw_refresh.worst_case_budget_s() + 300
    log_line({"event": "hw_refresh_start", "budget_s": budget,
              "steps": pending})
    with open(REFRESH_LOG, "a") as f:
        f.write(f"\n=== attempt at {time.strftime('%Y-%m-%dT%H:%M:%S')} "
                f"steps={','.join(pending)} ===\n")
        f.flush()
        p = subprocess.Popen(
            [sys.executable, os.path.join(REPO, "tools", "hw_refresh.py"),
             "--steps", ",".join(pending)],
            stdout=f, stderr=subprocess.STDOUT, cwd=REPO,
            start_new_session=True)
        try:
            rc = p.wait(timeout=budget)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(os.getpgid(p.pid), signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
            p.wait()
            rc = "timeout"
    log_line({"event": "hw_refresh_done", "rc": rc})
    return rc


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--max-hours", type=float, default=10.0)
    # r04 post-mortem (VERDICT r4 weak 5): the one healthy window in
    # 18 h lasted ~11 min, and 17-22 min probe spacing can miss a
    # sub-20-min window entirely.  480 s halves the detection latency;
    # the probe-kill-prolongs-wedge trade documented above still caps
    # how low this should go.
    ap.add_argument("--sleep-s", type=int, default=480)
    ap.add_argument("--once", action="store_true",
                    help="one probe, no refresh launch (health logging "
                         "only)")
    ap.add_argument("--cmd", default=None,
                    help="fire this shell command instead of hw_refresh "
                         "at the first healthy window (e.g. a one-off "
                         "A/B capture); exits 0 when it returns 0")
    ap.add_argument("--cmd-budget-s", type=int, default=1800,
                    help="kill --cmd's whole process group after this "
                         "many seconds (default 1800)")
    args = ap.parse_args()
    deadline = time.time() + args.max_hours * 3600
    fast_fails = 0
    refresh_attempts = 0
    while time.time() < deadline:
        ok, detail, wall = probe()
        log_line({"event": "probe", "ok": ok, "wall_s": wall,
                  "detail": detail})
        if args.once:
            return 0 if ok else 1
        if ok:
            rc = (run_cmd(args.cmd, args.cmd_budget_s) if args.cmd
                  else run_refresh())
            if rc == 0:
                return 0
            if args.cmd and rc != "timeout" and rc != 2:
                # hw_refresh retries are incremental (only non-green
                # steps re-run), but an arbitrary --cmd re-runs IN FULL
                # — and a deterministic nonzero exit (e.g. the A/B's
                # trajectory-mismatch verdict, rc 1) cannot change on
                # retry.  Retryable: a budget overrun ("timeout", the
                # wedge signature) and rc 2 (the capture tools'
                # convention for "transient: own probe failed, try a
                # later window" — swim_diss_ab.py).
                log_line({"event": "giving_up",
                          "reason": "--cmd failed deterministically "
                                    "(non-timeout rc); retrying would "
                                    "burn healthy windows on the same "
                                    "verdict", "last_rc": rc})
                return 1
            # partial/failed/timed-out refresh: the tunnel may have
            # re-wedged mid-run — keep probing and retry (bounded;
            # retries are incremental, re-running only non-green steps)
            refresh_attempts += 1
            # round 5 runs ten incremental steps (up from six): more
            # windows may be needed to land them all, and each retry
            # only re-runs the non-green steps, so extra attempts are
            # cheap when the tunnel is down and productive when it isn't
            if refresh_attempts >= 6:
                log_line({"event": "giving_up",
                          "reason": "6 refresh attempts without a "
                                    "fully-green run", "last_rc": rc})
                return 1
        if detail.startswith("fast-fail"):
            fast_fails += 1
            if fast_fails >= 3:
                log_line({"event": "giving_up",
                          "reason": "3 consecutive fast probe failures"})
                return 2
        else:
            fast_fails = 0
        time.sleep(max(0.0, min(args.sleep_s,
                                deadline - time.time())))
    log_line({"event": "deadline", "reason": "no healthy window"})
    return 3


if __name__ == "__main__":
    sys.exit(main())
