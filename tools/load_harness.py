#!/usr/bin/env python
"""Serving load harness: replay concurrent RPCs against a live
admission-batching sidecar and gate latency, throughput, and bitwise
per-request equality from the run ledger.

Two legs over the SAME request mix (a few distinct configs x distinct
seeds, ``curve=True``, ``engine="xla"`` so the solo auto-routing cannot
pick a different kernel family on TPU):

  * **solo** — today's per-request dispatch: ``serve(batching=None)``,
    every RPC runs ``run_simulation`` individually;
  * **batched** — ``serve(batching=ServingConfig(...))``: the admission
    batcher coalesces concurrent requests into per-tick megabatches
    (rpc/batcher + parallel/sweep.request_sweep_curves).

Both legs are warmed before measurement (per-config solo executables;
per-(key, lane-bucket) megabatch executables, driven directly so the
in-process jit cache covers every pow2 batch size the ticks can form),
so the measured window is steady-state serving: the gate requires every
measurement-phase ``batch`` event to report ``compiles == 0`` — p50
never touches the compile path.

Gates (exit 1 on any failure, ledgered as one ``serving_gate`` event):

  * batched requests/sec >= ``--min-ratio`` x solo requests/sec at the
    equal request mix (the acceptance line is 3x);
  * per-request BITWISE equality: each batched reply's curve / msgs /
    coverage / rounds equal its solo reply's bytes exactly;
  * steady-state all-warm: zero backend compiles inside the batched
    measurement window.

The ledger (provenance-stamped, utils/telemetry) carries the per-tick
``batch`` events from the server (same process, ambient ledger), one
``load_leg`` summary per leg with p50/p95/p99 latency and rps, and the
final gate verdict — this file IS the committed serving evidence
(artifacts/ledger_serving_r14.jsonl), re-asserted by a tier-1 pin and
rendered by tools/batching_report.py.

    python tools/load_harness.py --out artifacts/ledger_serving_r14.jsonl
    python tools/load_harness.py --smoke     # tiny live batch, no ratio gate
"""

import argparse
import json
import os
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def request_mix(n=256, rounds=16, fanout=2, repeats=8, seed0=0):
    """The equal request mix both legs replay: four protocol shapes —
    push-pull under a churn schedule (a partition window mid-run),
    pull under a static fault, plain push, and period-2 anti-entropy
    under link loss — each repeated with distinct seeds.  All four are
    batchable under ONE batch key (same n-bucket / fanout / rounds),
    so the megabatch mixes modes, faults, and schedules per tick."""
    shapes = [
        ({"mode": "pushpull", "fanout": fanout},
         {"drop_prob": 0.05, "seed": 3,
          "churn": {"events": [[3, 1, 4]],
                    "partitions": [[1, 3, n // 2]]}}),
        ({"mode": "pull", "fanout": fanout},
         {"node_death_rate": 0.05, "drop_prob": 0.05, "seed": 5}),
        ({"mode": "push", "fanout": fanout}, None),
        ({"mode": "antientropy", "fanout": fanout, "period": 2},
         {"drop_prob": 0.1, "seed": 7}),
    ]
    reqs = []
    for r in range(repeats):
        for i, (proto, fault) in enumerate(shapes):
            req = {"backend": "jax-tpu", "proto": proto,
                   "topology": {"family": "complete", "n": n},
                   "run": {"max_rounds": rounds, "engine": "xla",
                           "seed": seed0 + 31 * r + i},
                   "curve": True}
            if fault is not None:
                req["fault"] = fault
            reqs.append(req)
    return reqs


def distinct_requests(requests):
    """One request per distinct compiled SHAPE: everything except the
    ``run`` block keys the executable (seeds/targets are runtime
    operands).  The ONE definition of that assumption — the solo
    warmup, the fleet-leg warmup, and tools/fleet_crashloop.py all
    dedup through it, so a future shape-affecting field cannot leave
    one of them cold-compiling inside a measured window."""
    seen, out = set(), []
    for req in requests:
        sig = json.dumps({k: v for k, v in req.items()
                          if k != "run"}, sort_keys=True)
        if sig not in seen:
            seen.add(sig)
            out.append(req)
    return out


def _warm_megabatch(requests, serving_cfg):
    """Compile every (batch-key, pow2-lane-bucket) megabatch executable
    the ticks can form, directly through the driver — steady-state
    serving must never touch the compile path (the gate below)."""
    from gossip_tpu.backend import request_to_args
    from gossip_tpu.parallel.sweep import request_sweep_curves
    from gossip_tpu.rpc.batcher import classify_run, _topo_for
    by_key = {}
    for req in requests:
        key, spec, _ = classify_run(request_to_args(dict(req)))
        if key is None:
            raise SystemExit(f"load mix contains an unbatchable "
                             f"request: {spec}")
        by_key.setdefault(key, []).append(spec)
    from gossip_tpu.parallel.sweep import _pow2_at_least
    for key, specs in by_key.items():
        max_lanes = _pow2_at_least(min(len(specs),
                                       serving_cfg.max_batch))
        lanes = 1
        while lanes <= max_lanes:
            batch = (specs * lanes)[:lanes]
            # full=True matches the batcher's lowering exactly: one
            # executable per (key, lane bucket), whatever mode mix a
            # tick forms
            request_sweep_curves(batch, topo=_topo_for(key.topology),
                                 n_pad=(None if key.topology is not None
                                        else key.n_bucket), lanes=lanes,
                                 full=True)
            lanes *= 2
    return sorted(by_key, key=str)


def run_leg(label, requests, workers, serving_cfg, timeout_s, led,
            address=None):
    """One measured leg: serve in-process, replay the mix from
    ``workers`` concurrent client threads, return (summary, replies).
    ``address`` targets an ALREADY-RUNNING server (the fleet-router
    leg) instead of spinning an in-process sidecar."""
    from gossip_tpu.rpc.sidecar import SidecarClient, serve
    from gossip_tpu.utils import telemetry
    server = port = None
    if address is None:
        server, port = serve(port=0, max_workers=workers + 4,
                             batching=serving_cfg)
        address = f"127.0.0.1:{port}"
    n_req = len(requests)
    replies = [None] * n_req
    lat_ms = [None] * n_req
    errors = []
    cursor = {"i": 0}
    lock = threading.Lock()

    def worker():
        client = SidecarClient(address, max_attempts=1)
        while True:
            with lock:
                i = cursor["i"]
                if i >= n_req:
                    break
                cursor["i"] = i + 1
            t0 = time.perf_counter()
            try:
                replies[i] = client.run(timeout=timeout_s,
                                        **requests[i])
            except Exception as e:          # ledgered, gated below
                errors.append(f"req {i}: {type(e).__name__}: "
                              f"{str(e).splitlines()[0][:200]}")
            lat_ms[i] = (time.perf_counter() - t0) * 1e3
        client.close()
    led.event("load_phase", leg=label, phase="measure_start")
    t0 = time.perf_counter()
    threads = [threading.Thread(target=worker) for _ in range(workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    led.event("load_phase", leg=label, phase="measure_end")
    if server is not None:
        if server.gossip_batcher is not None:
            server.gossip_batcher.close()
        server.stop(grace=None)
    lat = [x for x in lat_ms if x is not None]
    summary = {
        "leg": label, "requests": n_req, "workers": workers,
        "errors": len(errors), "wall_s": round(wall, 3),
        "rps": round(n_req / wall, 2),
        "p50_ms": round(telemetry.percentile(lat, 0.50), 1),
        "p95_ms": round(telemetry.percentile(lat, 0.95), 1),
        "p99_ms": round(telemetry.percentile(lat, 0.99), 1),
    }
    led.event("load_leg", **summary)
    for msg in errors[:10]:
        led.event("load_error", leg=label, error=msg)
    return summary, replies


def compare_replies(batched, solo):
    """Per-request bitwise equality of the serving payload: curve (the
    exact float lists as serialized), msgs, coverage, rounds.  Returns
    the list of mismatch descriptions (empty == bitwise equal)."""
    bad = []
    for i, (b, s) in enumerate(zip(batched, solo)):
        if b is None or s is None:
            bad.append(f"req {i}: missing reply "
                       f"(batched={b is not None}, solo={s is not None})")
            continue
        for field in ("curve", "msgs", "coverage", "rounds"):
            if b.get(field) != s.get(field):
                bad.append(f"req {i}: {field} differs")
                break
    return bad


def measure_window_batch_events(path, run_id):
    """The ``batch`` events inside the batched leg's measurement window
    (between its load_phase markers) — the steady-all-warm gate's
    evidence."""
    from gossip_tpu.utils import telemetry
    events = telemetry.load_ledger(path, run=run_id)
    out, active = [], False
    for e in events:
        if e.get("ev") == "load_phase" and e.get("leg") == "batched":
            active = e.get("phase") == "measure_start"
        elif e.get("ev") == "batch" and active:
            out.append(e)
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--n", type=int, default=256)
    ap.add_argument("--rounds", type=int, default=16)
    ap.add_argument("--fanout", type=int, default=2)
    ap.add_argument("--repeats", type=int, default=16,
                    help="repeats of the 4-shape mix (requests = 4x)")
    ap.add_argument("--workers", type=int, default=24)
    ap.add_argument("--tick-ms", type=float, default=25.0)
    ap.add_argument("--max-batch", type=int, default=64)
    ap.add_argument("--min-ratio", type=float, default=3.0,
                    help="batched/solo rps acceptance (0 disables)")
    ap.add_argument("--timeout-s", type=float, default=300.0)
    ap.add_argument("--fleet-replicas", type=int, default=0,
                    help="also run the replica-count leg: the same "
                         "mix through a fronting router over N "
                         "spawned sidecar replicas (rpc/router, "
                         "docs/SERVING.md \"Fleet\") — gates bitwise "
                         "reply equality vs the solo leg and ledgers "
                         "a fleet load_leg (0 = off)")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny live batch: 2 repeats, 4 workers, no "
                         "throughput gate (equality + all-warm still "
                         "gate)")
    ap.add_argument("--out", default=None,
                    help="ledger path (default: a temp file; the "
                         "committed capture passes artifacts/"
                         "ledger_serving_r14.jsonl)")
    args = ap.parse_args(argv)
    if args.smoke:
        args.repeats = min(args.repeats, 2)
        args.workers = min(args.workers, 4)
        args.n = min(args.n, 128)
        args.rounds = min(args.rounds, 8)
        args.min_ratio = 0.0

    from gossip_tpu.config import ServingConfig
    from gossip_tpu.utils import telemetry
    out_path = args.out
    if not out_path:
        import tempfile
        fd, out_path = tempfile.mkstemp(prefix="gossip_serving_",
                                        suffix=".jsonl")
        os.close(fd)
    led = telemetry.Ledger(out_path)
    prev = telemetry.activate(led)
    try:
        led.record_runtime()
        requests = request_mix(n=args.n, rounds=args.rounds,
                               fanout=args.fanout,
                               repeats=args.repeats)
        serving = ServingConfig(tick_ms=args.tick_ms,
                                max_batch=args.max_batch,
                                max_queue=max(4 * args.max_batch, 256))
        led.event("load_config", requests=len(requests),
                  workers=args.workers, n=args.n, rounds=args.rounds,
                  tick_ms=args.tick_ms, max_batch=args.max_batch,
                  smoke=bool(args.smoke))

        # -- warmup (unmeasured): solo executables per distinct config,
        # megabatch executables per (key, lane bucket) ---------------
        led.event("load_phase", leg="warmup", phase="start")
        from gossip_tpu.backend import request_to_args, run_simulation
        distinct = distinct_requests(requests)
        for req in distinct:
            run_simulation(**request_to_args(dict(req)))
        keys = _warm_megabatch(requests, serving)
        led.event("load_phase", leg="warmup", phase="end",
                  distinct_configs=len(distinct),
                  batch_keys=len(keys))

        solo, solo_replies = run_leg("solo", requests, args.workers,
                                     None, args.timeout_s, led)
        batched, batched_replies = run_leg("batched", requests,
                                           args.workers, serving,
                                           args.timeout_s, led)

        fleet_ok = True
        if args.fleet_replicas > 0:
            from gossip_tpu.config import FleetConfig
            from gossip_tpu.rpc.router import Fleet, fleet_env
            from gossip_tpu.rpc.sidecar import SidecarClient
            fleet = Fleet(
                cfg=FleetConfig(replicas=args.fleet_replicas,
                                max_inflight=max(8, args.workers)),
                env=fleet_env(), max_workers=args.workers + 4)
            try:
                if not fleet.router.wait_healthy(args.fleet_replicas,
                                                 timeout_s=60):
                    raise SystemExit("fleet never reached full "
                                     "health")
                # warm each replica directly (the router steers
                # serial traffic at the least-loaded replica)
                for r in fleet.router.replicas:
                    c = SidecarClient(r.address, max_attempts=1)
                    for req in distinct_requests(requests):
                        c.run(timeout=args.timeout_s, **req)
                    c.close()
                fleet_sum, fleet_replies = run_leg(
                    f"fleet_r{args.fleet_replicas}", requests,
                    args.workers, None, args.timeout_s, led,
                    address=fleet.address)
                fleet_mismatch = compare_replies(fleet_replies,
                                                 solo_replies)
                for m in fleet_mismatch[:10]:
                    led.event("equality_mismatch", leg="fleet",
                              detail=m)
                fleet_ok = (not fleet_mismatch
                            and not fleet_sum["errors"])
                led.event("fleet_gate", ok=fleet_ok,
                          replicas=args.fleet_replicas,
                          bitwise_equal=not fleet_mismatch,
                          mismatches=len(fleet_mismatch),
                          rps=fleet_sum["rps"],
                          stats=fleet.router.stats())
            finally:
                fleet.close()

        mismatches = compare_replies(batched_replies, solo_replies)
        for m in mismatches[:10]:
            led.event("equality_mismatch", detail=m)
        batch_evs = measure_window_batch_events(out_path, led.run_id)
        compiles = sum(e.get("compiles") or 0 for e in batch_evs)
        sizes = [e.get("batch_size", 0) for e in batch_evs]
        ratio = (batched["rps"] / solo["rps"]) if solo["rps"] else 0.0
        coalesced = any(s > 1 for s in sizes)
        ok_ratio = (args.min_ratio <= 0) or (ratio >= args.min_ratio)
        ok = (ok_ratio and not mismatches and compiles == 0
              and not solo["errors"] and not batched["errors"]
              and coalesced and fleet_ok)
        led.event("serving_gate", ok=ok,
                  throughput_ratio=round(ratio, 2),
                  min_ratio=args.min_ratio, ratio_ok=ok_ratio,
                  bitwise_equal=not mismatches,
                  mismatches=len(mismatches),
                  steady_all_warm=compiles == 0,
                  measure_compiles=compiles,
                  batch_events=len(batch_evs),
                  max_batch_size=max(sizes) if sizes else 0,
                  coalesced=coalesced,
                  solo=solo, batched=batched)
        print(json.dumps({"ok": ok, "ratio": round(ratio, 2),
                          "solo_rps": solo["rps"],
                          "batched_rps": batched["rps"],
                          "batched_p50_ms": batched["p50_ms"],
                          "bitwise_equal": not mismatches,
                          "steady_all_warm": compiles == 0,
                          "max_batch_size": max(sizes) if sizes else 0,
                          "ledger": out_path}))
        return 0 if ok else 1
    finally:
        telemetry.activate(prev)
        led.close()


if __name__ == "__main__":
    sys.exit(main())
