#!/usr/bin/env python
"""Serving load harness: replay concurrent RPCs against a live
admission-batching sidecar and gate latency, throughput, and bitwise
per-request equality from the run ledger.

Two legs over the SAME request mix (a few distinct configs x distinct
seeds, ``curve=True``, ``engine="xla"`` so the solo auto-routing cannot
pick a different kernel family on TPU):

  * **solo** — today's per-request dispatch: ``serve(batching=None)``,
    every RPC runs ``run_simulation`` individually;
  * **batched** — ``serve(batching=ServingConfig(...))``: the admission
    batcher coalesces concurrent requests into per-tick megabatches
    (rpc/batcher + parallel/sweep.request_sweep_curves).

Both legs are warmed before measurement (per-config solo executables;
per-(key, lane-bucket) megabatch executables, driven directly so the
in-process jit cache covers every pow2 batch size the ticks can form),
so the measured window is steady-state serving: the gate requires every
measurement-phase ``batch`` event to report ``compiles == 0`` — p50
never touches the compile path.

Gates (exit 1 on any failure, ledgered as one ``serving_gate`` event):

  * batched requests/sec >= ``--min-ratio`` x solo requests/sec at the
    equal request mix (the acceptance line is 3x);
  * per-request BITWISE equality: each batched reply's curve / msgs /
    coverage / rounds equal its solo reply's bytes exactly;
  * steady-state all-warm: zero backend compiles inside the batched
    measurement window.

The ledger (provenance-stamped, utils/telemetry) carries the per-tick
``batch`` events from the server (same process, ambient ledger), one
``load_leg`` summary per leg with p50/p95/p99 latency and rps, and the
final gate verdict — this file IS the committed serving evidence
(artifacts/ledger_serving_r14.jsonl), re-asserted by a tier-1 pin and
rendered by tools/batching_report.py.

    python tools/load_harness.py --out artifacts/ledger_serving_r14.jsonl
    python tools/load_harness.py --smoke     # tiny live batch, no ratio gate

**Meshserve mode** (``--mesh-devices``): the thousands-of-concurrent-
connections capture for mesh-sharded replicas (docs/SERVING.md
"Mesh-sharded replicas").  One leg per (replica count x devices-per-
replica) pair over the SAME request list at FIXED concurrency — every
request rides its own client connection (one channel + thread each),
so ``--connections`` IS the concurrency.  Replica-count 1 legs serve
in-process (their per-tick ``batch`` events land on this ledger — the
steady-all-warm gate's evidence); replica counts > 1 spawn a Fleet
with ``devices_per_replica`` threading the host-device-count env.
Every leg's replies are gated BITWISE against driver-computed
references (the solo-parity + composition-invariance contracts make
one reference set serve every leg), and the final ``meshserve_gate``
requires the widest-mesh leg to beat the 1-device leg on rps at this
fixed concurrency by ``--mesh-min-ratio`` (the acceptance line is
1.5x).  The committed capture runs on the 4-device CPU mesh (the
XLA host-device count is set automatically when jax is not yet
loaded):

    python tools/load_harness.py --mesh-devices 1,4 \
        --out artifacts/ledger_meshserve_r21.jsonl
"""

import argparse
import json
import os
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def request_mix(n=256, rounds=16, fanout=2, repeats=8, seed0=0):
    """The equal request mix both legs replay: four protocol shapes —
    push-pull under a churn schedule (a partition window mid-run),
    pull under a static fault, plain push, and period-2 anti-entropy
    under link loss — each repeated with distinct seeds.  All four are
    batchable under ONE batch key (same n-bucket / fanout / rounds),
    so the megabatch mixes modes, faults, and schedules per tick."""
    shapes = [
        ({"mode": "pushpull", "fanout": fanout},
         {"drop_prob": 0.05, "seed": 3,
          "churn": {"events": [[3, 1, 4]],
                    "partitions": [[1, 3, n // 2]]}}),
        ({"mode": "pull", "fanout": fanout},
         {"node_death_rate": 0.05, "drop_prob": 0.05, "seed": 5}),
        ({"mode": "push", "fanout": fanout}, None),
        ({"mode": "antientropy", "fanout": fanout, "period": 2},
         {"drop_prob": 0.1, "seed": 7}),
    ]
    reqs = []
    for r in range(repeats):
        for i, (proto, fault) in enumerate(shapes):
            req = {"backend": "jax-tpu", "proto": proto,
                   "topology": {"family": "complete", "n": n},
                   "run": {"max_rounds": rounds, "engine": "xla",
                           "seed": seed0 + 31 * r + i},
                   "curve": True}
            if fault is not None:
                req["fault"] = fault
            reqs.append(req)
    return reqs


def distinct_requests(requests):
    """One request per distinct compiled SHAPE: everything except the
    ``run`` block keys the executable (seeds/targets are runtime
    operands).  The ONE definition of that assumption — the solo
    warmup, the fleet-leg warmup, and tools/fleet_crashloop.py all
    dedup through it, so a future shape-affecting field cannot leave
    one of them cold-compiling inside a measured window."""
    seen, out = set(), []
    for req in requests:
        sig = json.dumps({k: v for k, v in req.items()
                          if k != "run"}, sort_keys=True)
        if sig not in seen:
            seen.add(sig)
            out.append(req)
    return out


def _group_by_key(requests):
    """``{BatchKey: [(index, spec), ...]}`` for a batchable request
    list (index-preserving, so references map back to reply slots)."""
    from gossip_tpu.backend import request_to_args
    from gossip_tpu.rpc.batcher import classify_run
    by_key = {}
    for i, req in enumerate(requests):
        key, spec, _ = classify_run(request_to_args(dict(req)))
        if key is None:
            raise SystemExit(f"load mix contains an unbatchable "
                             f"request: {spec}")
        by_key.setdefault(key, []).append((i, spec))
    return by_key


def _warm_megabatch(requests, serving_cfg, devices=1):
    """Compile every (batch-key, pow2-lane-bucket) megabatch executable
    the ticks can form, directly through the driver — steady-state
    serving must never touch the compile path (the gate below).
    ``devices > 1`` warms the MESH lowering the batcher will use: the
    same lane buckets floored at the device count, dispatched on the
    replica mesh (rpc/batcher mesh dispatch — one executable per
    (key, bucket) there too, jit re-specializing on shardings)."""
    from gossip_tpu.parallel.sweep import (_pow2_at_least,
                                           request_sweep_curves)
    from gossip_tpu.rpc.batcher import Batcher, _topo_for
    mesh = Batcher._build_mesh(devices)
    by_key = _group_by_key(requests)
    for key, entries in by_key.items():
        specs = [s for _, s in entries]
        max_lanes = _pow2_at_least(min(len(specs),
                                       serving_cfg.max_batch),
                                   devices)
        lanes = max(1, devices)
        while lanes <= max_lanes:
            batch = (specs * lanes)[:lanes]
            # full=True matches the batcher's lowering exactly: one
            # executable per (key, lane bucket), whatever mode mix a
            # tick forms
            request_sweep_curves(batch, topo=_topo_for(key.topology),
                                 n_pad=(None if key.topology is not None
                                        else key.n_bucket), lanes=lanes,
                                 mesh=mesh, full=True)
            lanes *= 2
    return sorted(by_key, key=str)


def reference_replies(requests, serving_cfg):
    """Driver-computed expected replies, one per request — the bitwise
    yardstick every meshserve leg is gated against.  Sound because of
    two PINNED contracts (tests/test_serving.py): megabatch rows equal
    solo ``simulate_curve`` bitwise, and rows are invariant to batch
    COMPOSITION — so chunking the request list through the no-mesh
    driver yields exactly the bytes any server leg (any mesh width,
    any tick grouping) must return.  Cheap: a handful of megabatches
    instead of thousands of solo dispatches."""
    from gossip_tpu.parallel.sweep import request_sweep_curves
    from gossip_tpu.rpc.batcher import _topo_for
    refs = [None] * len(requests)
    for key, entries in _group_by_key(requests).items():
        for at in range(0, len(entries), serving_cfg.max_batch):
            chunk = entries[at:at + serving_cfg.max_batch]
            res = request_sweep_curves(
                tuple(s for _, s in chunk),
                topo=_topo_for(key.topology),
                n_pad=(None if key.topology is not None
                       else key.n_bucket),
                full=True)
            for j, (i, _) in enumerate(chunk):
                curve = [float(c) for c in res.curves[j]]
                refs[i] = {"curve": curve, "coverage": curve[-1],
                           "msgs": float(res.msgs[j][-1]),
                           "rounds": int(res.rounds_to_target[j])}
    return refs


def run_leg(label, requests, workers, serving_cfg, timeout_s, led,
            address=None, devices=1, attempts=1):
    """One measured leg: serve in-process, replay the mix from
    ``workers`` concurrent client threads — each thread owns its OWN
    channel, so ``workers == len(requests)`` is the one-connection-
    per-request shape the meshserve capture uses — return (summary,
    replies).  ``address`` targets an ALREADY-RUNNING server (the
    fleet-router and multi-replica mesh legs) instead of spinning an
    in-process sidecar; ``devices`` labels the leg's mesh width in the
    ledger; ``attempts`` is the per-client UNAVAILABLE retry budget
    (replies are pure functions of their payload, so a retried request
    cannot change the bitwise gate — thousands of channels racing one
    accept loop need it)."""
    from gossip_tpu.rpc.sidecar import SidecarClient, serve
    from gossip_tpu.utils import telemetry
    server = port = None
    if address is None:
        server, port = serve(port=0, max_workers=workers + 4,
                             batching=serving_cfg)
        address = f"127.0.0.1:{port}"
    n_req = len(requests)
    replies = [None] * n_req
    lat_ms = [None] * n_req
    errors = []
    cursor = {"i": 0}
    lock = threading.Lock()

    def worker():
        client = SidecarClient(address, max_attempts=attempts)
        while True:
            with lock:
                i = cursor["i"]
                if i >= n_req:
                    break
                cursor["i"] = i + 1
            t0 = time.perf_counter()
            try:
                replies[i] = client.run(timeout=timeout_s,
                                        **requests[i])
            except Exception as e:          # ledgered, gated below
                errors.append(f"req {i}: {type(e).__name__}: "
                              f"{str(e).splitlines()[0][:200]}")
            lat_ms[i] = (time.perf_counter() - t0) * 1e3
        client.close()
    led.event("load_phase", leg=label, phase="measure_start")
    t0 = time.perf_counter()
    threads = [threading.Thread(target=worker) for _ in range(workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    led.event("load_phase", leg=label, phase="measure_end")
    if server is not None:
        if server.gossip_batcher is not None:
            server.gossip_batcher.close()
        server.stop(grace=None)
    lat = [x for x in lat_ms if x is not None]
    summary = {
        "leg": label, "requests": n_req, "workers": workers,
        "devices": devices,
        "errors": len(errors), "wall_s": round(wall, 3),
        "rps": round(n_req / wall, 2),
        "p50_ms": round(telemetry.percentile(lat, 0.50), 1),
        "p95_ms": round(telemetry.percentile(lat, 0.95), 1),
        "p99_ms": round(telemetry.percentile(lat, 0.99), 1),
    }
    led.event("load_leg", **summary)
    for msg in errors[:10]:
        led.event("load_error", leg=label, error=msg)
    return summary, replies


def compare_replies(batched, solo):
    """Per-request bitwise equality of the serving payload: curve (the
    exact float lists as serialized), msgs, coverage, rounds.  Returns
    the list of mismatch descriptions (empty == bitwise equal)."""
    bad = []
    for i, (b, s) in enumerate(zip(batched, solo)):
        if b is None or s is None:
            bad.append(f"req {i}: missing reply "
                       f"(batched={b is not None}, solo={s is not None})")
            continue
        for field in ("curve", "msgs", "coverage", "rounds"):
            if b.get(field) != s.get(field):
                bad.append(f"req {i}: {field} differs")
                break
    return bad


def measure_window_batch_events(path, run_id, leg="batched"):
    """The ``batch`` events inside one leg's measurement window
    (between its load_phase markers) — the steady-all-warm gate's
    evidence.  ``leg`` picks the window: "batched" for the classic
    capture, "mesh_r1_dK" per in-process meshserve leg."""
    from gossip_tpu.utils import telemetry
    events = telemetry.load_ledger(path, run=run_id)
    out, active = [], False
    for e in events:
        if e.get("ev") == "load_phase" and e.get("leg") == leg:
            active = e.get("phase") == "measure_start"
        elif e.get("ev") == "batch" and active:
            out.append(e)
    return out


def emit_trace_join(led, out_path):
    """Join this run's request traces (tools/trace_report — the ONE
    waterfall-join implementation) and ledger the summary plus the
    attributed p99 exemplars as a ``trace_join`` event, so the
    committed capture carries its own tail-latency decomposition.
    In-process legs land BOTH request_trace halves on this ledger
    (clients mint trace ids, the server shares the ambient ledger);
    fleet-leg replica subprocesses write no ledger here, so their
    traces join router-half-only — reported, never gated."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    try:
        import trace_report
    finally:
        sys.path.pop(0)
    events = trace_report.load_events([out_path])
    rows = trace_report.waterfalls(events)
    if not rows:
        return None
    summary = trace_report.summarize(rows)
    led.event("trace_join", **summary,
              exemplars=trace_report.exemplars(rows, k=3))
    return summary


def _ensure_host_devices(k):
    """Best-effort XLA host-device-count pin for the meshserve capture:
    only effective BEFORE the first jax import (XLA_FLAGS is read at
    backend init) and only on the CPU platform.  When jax is already
    loaded the ambient device count stands — the Batcher then refuses
    loudly if it cannot build the requested mesh, so a silent 1-device
    capture is impossible either way."""
    if k <= 1 or "jax" in sys.modules:
        return
    if os.environ.get("JAX_PLATFORMS", "cpu") not in ("", "cpu"):
        return
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={k}"
        ).strip()


# when the host cannot express the mesh's device parallelism at all
# (fewer schedulable CPUs than devices: every "device" timeshares one
# core), the scaling leg is UNRESOLVED — the ratio gate then only
# requires the mesh not to regress the solo path beyond thread-harness
# noise, and the gate event records scaling_resolved=false so no
# downstream consumer can mistake the capture for scaling evidence
# (same philosophy as fleet legs' measure_compiles=None: ledgered as
# unmeasured, never silently green).  The >= --mesh-min-ratio check
# arms itself automatically on any host with enough cores — the
# hw_refresh mesh_serving step is where that recapture rides.
_SERIAL_HOST_FLOOR = 0.85


def run_meshserve(args, led, out_path):
    """The per-(replica count x devices-per-replica) capture: warm the
    driver for every mesh width, compute the bitwise reference set
    once, then one fixed-concurrency leg per pair — finally the
    ``meshserve_gate``: widest-mesh rps >= ``--mesh-min-ratio`` x
    1-device rps (on hosts whose CPU count can express the device
    parallelism — see ``_SERIAL_HOST_FLOOR``), bitwise parity on EVERY
    leg, zero compiles in every in-process measured window."""
    from gossip_tpu.config import ServingConfig
    devices_list = sorted({int(d) for d in
                           args.mesh_devices.split(",") if d})
    replicas_list = sorted({int(r) for r in
                            args.mesh_replicas.split(",") if r})
    connections = args.connections
    # a 2 MiB stack per client/handler thread: thousands of threads at
    # the default 8 MiB would be pure address-space waste (they only
    # drive a channel / wait on a tick); the collector thread runs only
    # warm dispatch inside the measured window
    if connections >= 512:
        threading.stack_size(2 * 1024 * 1024)
    base = request_mix(n=args.n, rounds=args.rounds,
                       fanout=args.fanout,
                       repeats=(connections + 3) // 4)
    requests = base[:connections]
    led.event("load_config", mode="meshserve",
              requests=len(requests), connections=connections,
              devices_legs=devices_list, replicas_legs=replicas_list,
              n=args.n, rounds=args.rounds, tick_ms=args.tick_ms,
              max_batch=args.max_batch, smoke=bool(args.smoke))

    def cfg_for(devs):
        return ServingConfig(tick_ms=args.tick_ms,
                             max_batch=args.max_batch,
                             max_queue=connections + 256,
                             devices=devs)

    led.event("load_phase", leg="warmup", phase="start")
    refs = reference_replies(requests, cfg_for(1))
    for devs in devices_list:
        _warm_megabatch(requests, cfg_for(devs), devices=devs)
    led.event("load_phase", leg="warmup", phase="end",
              references=len(refs))

    legs, mismatch_total, errors_total, compiles_total = {}, 0, 0, 0
    for reps in replicas_list:
        for devs in devices_list:
            label = f"mesh_r{reps}_d{devs}"
            if reps == 1:
                summary, replies = run_leg(
                    label, requests, connections, cfg_for(devs),
                    args.timeout_s, led, devices=devs, attempts=4)
                evs = measure_window_batch_events(out_path, led.run_id,
                                                  leg=label)
                compiles = sum(e.get("compiles") or 0 for e in evs)
                summary["measure_compiles"] = compiles
                compiles_total += compiles
            else:
                from gossip_tpu.config import FleetConfig
                from gossip_tpu.rpc.router import Fleet, fleet_env
                from gossip_tpu.rpc.sidecar import SidecarClient
                fleet = Fleet(
                    cfg=FleetConfig(replicas=reps,
                                    devices_per_replica=devs,
                                    max_inflight=connections),
                    replica_argv=(("--devices", str(devs))
                                  if devs > 1 else ()),
                    env=fleet_env(devices=devs),
                    max_workers=connections + 4)
                try:
                    if not fleet.router.wait_healthy(reps,
                                                     timeout_s=60):
                        raise SystemExit(f"{label}: fleet never "
                                         "reached full health")
                    for r in fleet.router.replicas:
                        c = SidecarClient(r.address, max_attempts=1)
                        for req in distinct_requests(requests):
                            c.run(timeout=args.timeout_s, **req)
                        c.close()
                    summary, replies = run_leg(
                        label, requests, connections, None,
                        args.timeout_s, led, address=fleet.address,
                        devices=devs, attempts=4)
                    # child compiles are invisible to this ledger, so
                    # the all-warm gate covers in-process legs only —
                    # ledgered as unmeasured, never silently green
                    summary["measure_compiles"] = None
                finally:
                    fleet.close()
            bad = compare_replies(replies, refs)
            for m in bad[:10]:
                led.event("equality_mismatch", leg=label, detail=m)
            summary["bitwise_equal"] = not bad
            mismatch_total += len(bad)
            errors_total += summary["errors"]
            legs[label] = summary

    base_leg = legs.get(f"mesh_r1_d{devices_list[0]}")
    peak_leg = legs.get(f"mesh_r1_d{devices_list[-1]}")
    ratio = (peak_leg["rps"] / base_leg["rps"]
             if base_leg and peak_leg and base_leg["rps"] else 0.0)
    try:
        sched_cpus = len(os.sched_getaffinity(0))
    except AttributeError:                  # non-Linux fallback
        sched_cpus = os.cpu_count() or 1
    scaling_resolved = sched_cpus >= devices_list[-1]
    if args.mesh_min_ratio <= 0:
        ok_ratio = True
    elif scaling_resolved:
        ok_ratio = ratio >= args.mesh_min_ratio
    else:
        # the host cannot express the device parallelism (every
        # device timeshares sched_cpus < peak cores): the scaling leg
        # is unresolved, not passed — gate only that the mesh path
        # does not regress the solo path beyond harness noise
        ok_ratio = ratio >= _SERIAL_HOST_FLOOR
    ok = (ok_ratio and mismatch_total == 0 and errors_total == 0
          and compiles_total == 0)
    led.event("meshserve_gate", ok=ok,
              devices_ratio=round(ratio, 2),
              min_ratio=args.mesh_min_ratio, ratio_ok=ok_ratio,
              sched_cpus=sched_cpus,
              scaling_resolved=scaling_resolved,
              serial_host_floor=(None if scaling_resolved
                                 else _SERIAL_HOST_FLOOR),
              connections=connections,
              base_devices=devices_list[0],
              peak_devices=devices_list[-1],
              bitwise_equal=mismatch_total == 0,
              mismatches=mismatch_total,
              steady_all_warm=compiles_total == 0,
              measure_compiles=compiles_total,
              errors=errors_total, legs=legs)
    emit_trace_join(led, out_path)
    print(json.dumps({"ok": ok, "mode": "meshserve",
                      "devices_ratio": round(ratio, 2),
                      "scaling_resolved": scaling_resolved,
                      "sched_cpus": sched_cpus,
                      "connections": connections,
                      "legs": {k: {"rps": v["rps"],
                                   "p99_ms": v["p99_ms"]}
                               for k, v in legs.items()},
                      "bitwise_equal": mismatch_total == 0,
                      "steady_all_warm": compiles_total == 0,
                      "ledger": out_path}))
    return 0 if ok else 1


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--n", type=int, default=256)
    ap.add_argument("--rounds", type=int, default=16)
    ap.add_argument("--fanout", type=int, default=2)
    ap.add_argument("--repeats", type=int, default=16,
                    help="repeats of the 4-shape mix (requests = 4x)")
    ap.add_argument("--workers", type=int, default=24)
    ap.add_argument("--tick-ms", type=float, default=25.0)
    ap.add_argument("--max-batch", type=int, default=64)
    ap.add_argument("--min-ratio", type=float, default=3.0,
                    help="batched/solo rps acceptance (0 disables)")
    ap.add_argument("--timeout-s", type=float, default=300.0)
    ap.add_argument("--fleet-replicas", type=int, default=0,
                    help="also run the replica-count leg: the same "
                         "mix through a fronting router over N "
                         "spawned sidecar replicas (rpc/router, "
                         "docs/SERVING.md \"Fleet\") — gates bitwise "
                         "reply equality vs the solo leg and ledgers "
                         "a fleet load_leg (0 = off)")
    ap.add_argument("--mesh-devices", default=None,
                    help="meshserve mode: comma list of devices-per-"
                         "replica leg widths (e.g. '1,4'); switches "
                         "the capture to fixed-concurrency mesh legs "
                         "gated by meshserve_gate (docs/SERVING.md "
                         "\"Mesh-sharded replicas\")")
    ap.add_argument("--mesh-replicas", default="1",
                    help="meshserve mode: comma list of replica "
                         "counts to cross with --mesh-devices "
                         "(replicas > 1 spawn a Fleet with "
                         "devices_per_replica)")
    ap.add_argument("--connections", type=int, default=2048,
                    help="meshserve mode: concurrent client "
                         "connections = requests per leg (one channel "
                         "+ thread each; the fixed-concurrency axis)")
    ap.add_argument("--mesh-min-ratio", type=float, default=1.5,
                    help="meshserve acceptance: widest-mesh rps / "
                         "1-device rps at fixed concurrency "
                         "(0 disables)")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny live batch: 2 repeats, 4 workers, no "
                         "throughput gate (equality + all-warm still "
                         "gate)")
    ap.add_argument("--out", default=None,
                    help="ledger path (default: a temp file; the "
                         "committed captures pass artifacts/"
                         "ledger_serving_r14.jsonl / "
                         "ledger_meshserve_r21.jsonl)")
    args = ap.parse_args(argv)
    if args.smoke:
        args.repeats = min(args.repeats, 2)
        args.workers = min(args.workers, 4)
        args.n = min(args.n, 128)
        args.rounds = min(args.rounds, 8)
        args.min_ratio = 0.0
        args.mesh_min_ratio = 0.0
        args.connections = min(args.connections, 64)
        if args.out and args.out.endswith(".jsonl"):
            # the tool owns its smoke infixing (hw_refresh convention:
            # a smoke rehearsal must never clobber a committed capture)
            args.out = args.out[:-len(".jsonl")] + ".smoke.jsonl"
    if args.mesh_devices:
        # BEFORE any jax-importing call: the widest leg needs that many
        # XLA host devices in this process
        _ensure_host_devices(max(int(d) for d in
                                 args.mesh_devices.split(",") if d))

    from gossip_tpu.config import ServingConfig
    from gossip_tpu.utils import telemetry
    out_path = args.out
    if not out_path:
        import tempfile
        fd, out_path = tempfile.mkstemp(prefix="gossip_serving_",
                                        suffix=".jsonl")
        os.close(fd)
    led = telemetry.Ledger(out_path)
    prev = telemetry.activate(led)
    try:
        led.record_runtime()
        if args.mesh_devices:
            return run_meshserve(args, led, out_path)
        requests = request_mix(n=args.n, rounds=args.rounds,
                               fanout=args.fanout,
                               repeats=args.repeats)
        serving = ServingConfig(tick_ms=args.tick_ms,
                                max_batch=args.max_batch,
                                max_queue=max(4 * args.max_batch, 256))
        led.event("load_config", requests=len(requests),
                  workers=args.workers, n=args.n, rounds=args.rounds,
                  tick_ms=args.tick_ms, max_batch=args.max_batch,
                  smoke=bool(args.smoke))

        # -- warmup (unmeasured): solo executables per distinct config,
        # megabatch executables per (key, lane bucket) ---------------
        led.event("load_phase", leg="warmup", phase="start")
        from gossip_tpu.backend import request_to_args, run_simulation
        distinct = distinct_requests(requests)
        for req in distinct:
            run_simulation(**request_to_args(dict(req)))
        keys = _warm_megabatch(requests, serving)
        led.event("load_phase", leg="warmup", phase="end",
                  distinct_configs=len(distinct),
                  batch_keys=len(keys))

        solo, solo_replies = run_leg("solo", requests, args.workers,
                                     None, args.timeout_s, led)
        batched, batched_replies = run_leg("batched", requests,
                                           args.workers, serving,
                                           args.timeout_s, led)

        fleet_ok = True
        if args.fleet_replicas > 0:
            from gossip_tpu.config import FleetConfig
            from gossip_tpu.rpc.router import Fleet, fleet_env
            from gossip_tpu.rpc.sidecar import SidecarClient
            fleet = Fleet(
                cfg=FleetConfig(replicas=args.fleet_replicas,
                                max_inflight=max(8, args.workers)),
                env=fleet_env(), max_workers=args.workers + 4)
            try:
                if not fleet.router.wait_healthy(args.fleet_replicas,
                                                 timeout_s=60):
                    raise SystemExit("fleet never reached full "
                                     "health")
                # warm each replica directly (the router steers
                # serial traffic at the least-loaded replica)
                for r in fleet.router.replicas:
                    c = SidecarClient(r.address, max_attempts=1)
                    for req in distinct_requests(requests):
                        c.run(timeout=args.timeout_s, **req)
                    c.close()
                fleet_sum, fleet_replies = run_leg(
                    f"fleet_r{args.fleet_replicas}", requests,
                    args.workers, None, args.timeout_s, led,
                    address=fleet.address)
                fleet_mismatch = compare_replies(fleet_replies,
                                                 solo_replies)
                for m in fleet_mismatch[:10]:
                    led.event("equality_mismatch", leg="fleet",
                              detail=m)
                fleet_ok = (not fleet_mismatch
                            and not fleet_sum["errors"])
                # rps alone hid latency regressions (the percentile
                # satellite): the gate event carries the leg's
                # p50/p95/p99 — the SAME telemetry.percentile values
                # run_leg computed, never a second definition — so
                # fleet latency is diffable (ledger_diff carries them
                # informationally; walls never gate)
                led.event("fleet_gate", ok=fleet_ok,
                          replicas=args.fleet_replicas,
                          bitwise_equal=not fleet_mismatch,
                          mismatches=len(fleet_mismatch),
                          rps=fleet_sum["rps"],
                          p50_ms=fleet_sum["p50_ms"],
                          p95_ms=fleet_sum["p95_ms"],
                          p99_ms=fleet_sum["p99_ms"],
                          stats=fleet.router.stats())
            finally:
                fleet.close()

        mismatches = compare_replies(batched_replies, solo_replies)
        for m in mismatches[:10]:
            led.event("equality_mismatch", detail=m)
        batch_evs = measure_window_batch_events(out_path, led.run_id)
        compiles = sum(e.get("compiles") or 0 for e in batch_evs)
        sizes = [e.get("batch_size", 0) for e in batch_evs]
        ratio = (batched["rps"] / solo["rps"]) if solo["rps"] else 0.0
        coalesced = any(s > 1 for s in sizes)
        ok_ratio = (args.min_ratio <= 0) or (ratio >= args.min_ratio)
        ok = (ok_ratio and not mismatches and compiles == 0
              and not solo["errors"] and not batched["errors"]
              and coalesced and fleet_ok)
        led.event("serving_gate", ok=ok,
                  throughput_ratio=round(ratio, 2),
                  min_ratio=args.min_ratio, ratio_ok=ok_ratio,
                  bitwise_equal=not mismatches,
                  mismatches=len(mismatches),
                  steady_all_warm=compiles == 0,
                  measure_compiles=compiles,
                  batch_events=len(batch_evs),
                  max_batch_size=max(sizes) if sizes else 0,
                  coalesced=coalesced,
                  solo=solo, batched=batched)
        traces = emit_trace_join(led, out_path)
        print(json.dumps({"ok": ok, "ratio": round(ratio, 2),
                          "traces": (traces or {}).get("traces", 0),
                          "complete_waterfalls":
                              (traces or {}).get("complete", 0),
                          "solo_rps": solo["rps"],
                          "batched_rps": batched["rps"],
                          "batched_p50_ms": batched["p50_ms"],
                          "bitwise_equal": not mismatches,
                          "steady_all_warm": compiles == 0,
                          "max_batch_size": max(sizes) if sizes else 0,
                          "ledger": out_path}))
        return 0 if ok else 1
    finally:
        telemetry.activate(prev)
        led.close()


if __name__ == "__main__":
    sys.exit(main())
