#!/usr/bin/env python
"""Backend-parity matrix: the r03 spot checks widened to a family x size
grid (VERDICT r3 item 4).

Runs ``gossip-tpu run --parity-check`` (jax-tpu flood rounds vs the
go-native event engine's hop depths — the C++ core above 20k nodes) over
every explicit family — {ring, grid, erdos_renyi} across {~1k, ~100k,
~1M}, plus watts_strogatz and power_law at the 100k-class size — and
writes ONE artifact, ``artifacts/parity_r05.json``, with every contract
metric per cell:

  * ``curve_gap``           — exactly 0.0 on 'exact'-tier rows (race-
    free graph AND power-of-two n: one jax round == one hop depth,
    point for point, with dyadic float32-exact coverage fractions);
    < 1e-6 on 'quantization'-tier rows (race-free, non-dyadic n).
  * ``hop_bound_violation`` — ~0 on EVERY graph: event-order races can
    only DELAY the event sim relative to the hop-depth bound.
  * ``fixed_point_gap``     — ~0 on every graph: both engines share the
    dedup+relay fixed point (reference main.go:113-118).

Cells run as subprocesses on the hermetic CPU env (parity is a
correctness artifact, not a perf number, and the TPU tunnel must stay
free for the watchdog/hw_refresh).  A cell that fails or times out is
recorded as a skipped row with its reason — no silent truncation.

    python tools/parity_matrix.py            # full matrix, ~20-40 min
    python tools/parity_matrix.py ring-1024  # named cells only
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ART = os.path.join(REPO, "artifacts", "parity_r05.json")

# Expectation tiers, measured before they were codified:
#   exact        — curve_gap EXACTLY 0.0: race-free graph (k=2 ring or
#                  2-D grid: empirically no delivery-order races) AND a
#                  power-of-two n (dyadic coverage fractions, float32-
#                  exact).
#   quantization — same race-free structure but non-dyadic n (the C++
#                  event core caps at exactly 1,000,000, so the big grid
#                  is 1000^2): curve_gap < 1e-6 is float32 rounding of
#                  k/n fractions, NOT parity disagreement.
#   racy         — event-order races delay the event sim (ER always;
#                  rings with k > 2: a node at depth d is reachable via
#                  multiple same-depth paths and the engine's
#                  delivery/retry interleaving can defer its relay), so
#                  only the one-sided bound and the fixed point hold.
EXACT, QUANT, RACY = "exact", "quantization", "racy"

# (name, extra argv, per-cell timeout s, tier)
CELLS = [
    ("ring-1024", ["--family", "ring", "--n", "1024", "--k", "2",
                   "--max-rounds", "600"], 300, EXACT),
    ("ring-131072", ["--family", "ring", "--n", "131072", "--k", "16",
                     "--max-rounds", "8400"], 1800, RACY),
    ("grid-1024", ["--family", "grid", "--n", "1024",
                   "--max-rounds", "200"], 300, EXACT),
    ("grid-65536", ["--family", "grid", "--n", "65536",
                    "--max-rounds", "600"], 1200, EXACT),
    ("grid-1000000", ["--family", "grid", "--n", "1000000",
                      "--max-rounds", "2200"], 3600, QUANT),
    ("er-1024", ["--family", "erdos_renyi", "--n", "1024", "--p", "0.01",
                 "--max-rounds", "64"], 300, RACY),
    # the two remaining explicit families, at the 100k-class size: both
    # racy (WS is a k>2 ring with shortcuts; power-law hubs multiply
    # same-depth paths), so they carry the bound + fixed-point contract
    ("ws-131072", ["--family", "watts_strogatz", "--n", "131072",
                   "--k", "8", "--p", "0.1", "--max-rounds", "200"],
     900, RACY),
    # measured ~400 s (the padded power-law table build dominates);
    # generous timeout so a slower machine doesn't turn it into a skip
    ("powerlaw-131072", ["--family", "power_law", "--n", "131072",
                         "--k", "3", "--max-rounds", "64"], 1800, RACY),
    ("er-131072", ["--family", "erdos_renyi", "--n", "131072",
                   "--p", "0.00009", "--max-rounds", "64"], 900, RACY),
    ("er-1000000", ["--family", "erdos_renyi", "--n", "1000000",
                    "--p", "0.000012", "--max-rounds", "64"], 1800, RACY),
]

# ring at 1M is structurally out of reach for a round-synchronous flood:
# diameter n/k needs a >15k-round program at any table size a 1M-row
# ring can afford (k=64 is already a 256 MB table); the ring family's
# 100k-class row carries the contract instead.
SKIPPED_BY_DESIGN = [
    {"cell": "ring-1048576",
     "reason": "flood diameter n/k: >15k rounds at any affordable ring "
               "degree; ring parity at scale is carried by ring-131072"}]


def cpu_env():
    """bench.py's hermetic CPU env — imported, not copied: it also pops
    the tunnel-arming hazard vars (PALLAS_AXON_POOL_IPS etc.), without
    which a wedged tunnel could burn a cell's whole timeout."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    try:
        from _bench import hermetic_cpu_env
    finally:
        sys.path.pop(0)
    return hermetic_cpu_env()


def run_cell(name, argv, timeout):
    """One --parity-check subprocess -> its JSON report (raises on
    failure; the caller records the reason)."""
    cmd = [sys.executable, "-m", "gossip_tpu", "run", "--parity-check",
           "--mode", "flood", "--backend", "jax-tpu", *argv]
    p = subprocess.run(cmd, capture_output=True, text=True,
                       timeout=timeout, cwd=REPO, env=cpu_env())
    if p.returncode != 0:
        raise RuntimeError((p.stderr or p.stdout)[-300:])
    return json.loads(p.stdout.strip().splitlines()[-1])


def main(only=None):
    if only:
        known = {c[0] for c in CELLS}
        bad = sorted(set(only) - known)
        if bad:
            # a typo must not read as an (empty) all-true contract
            print(f"unknown cells: {bad}; known: {sorted(known)}",
                  file=sys.stderr)
            return 2
    rows, skipped = {}, list(SKIPPED_BY_DESIGN)
    for name, argv, timeout, tier in CELLS:
        if only and name not in only:
            continue
        t0 = time.time()
        try:
            rep = run_cell(name, argv, timeout)
            rows[name] = {
                "curve_gap": rep["curve_gap"],
                "hop_bound_violation": rep["hop_bound_violation"],
                "fixed_point_gap": rep["fixed_point_gap"],
                "n": rep["n"], "family": rep["family"],
                "tier": tier,
                "gonative_engine": rep["gonative"]["meta"].get("engine"),
                "jax_rounds": rep["jax"]["rounds"],
                "jax_wall_s": rep["jax"]["wall_s"],
                "gonative_wall_s": rep["gonative"]["wall_s"],
                "cell_wall_s": round(time.time() - t0, 1),
            }
            print(json.dumps({name: rows[name]}), flush=True)
        except Exception as e:
            skipped.append({"cell": name,
                            "reason": f"{type(e).__name__}: {e}"[:300]})
            print(json.dumps({name: "SKIPPED", "reason": str(e)[:200]}),
                  flush=True)
    out = {
        "what": "backend-parity matrix via `gossip-tpu run "
                "--parity-check` (VERDICT r3 item 4): jax-tpu flood "
                "rounds vs the go-native event engine's hop depths on "
                "the same graph — ring/grid/er across {~1k, ~100k, ~1M} "
                "plus watts_strogatz and power_law at the 100k-class "
                "size. "
                "Contract by tier: 'exact' rows have curve_gap EXACTLY "
                "0.0 (race-free graph, power-of-two n -> dyadic float32 "
                "coverage); 'quantization' rows are race-free at "
                "non-dyadic n (curve_gap < 1e-6 is float32 rounding, "
                "not disagreement); 'racy' rows keep only the one-sided "
                "hop bound and the shared dedup+relay fixed point "
                "(reference main.go:113-118) — see tools/parity_matrix"
                ".py for why each cell has its tier.",
        "rows": rows, "skipped": skipped,
    }
    exact_ok = all(r["curve_gap"] == 0.0 and r["hop_bound_violation"] == 0.0
                   and r["fixed_point_gap"] == 0.0
                   for r in rows.values() if r["tier"] == EXACT)
    quant_ok = all(r["curve_gap"] < 1e-6 for r in rows.values()
                   if r["tier"] == QUANT)
    bound_ok = all(r["hop_bound_violation"] < 1e-6
                   and r["fixed_point_gap"] < 1e-6 for r in rows.values())
    # per-tier row counts ride with the verdicts: a filtered run's
    # vacuous all-true over an absent tier is visible as its 0 count
    tiers = [r["tier"] for r in rows.values()]
    out["contract"] = {"exact_rows": tiers.count(EXACT),
                       "exact_rows_exact": exact_ok,
                       "quantization_rows": tiers.count(QUANT),
                       "quantization_rows_below_1e6": quant_ok,
                       "rows_total": len(rows),
                       "bounds_all_rows": bound_ok,
                       "partial_selection": bool(only)}
    if only is None or not only:
        # the one artifact schema (tools/validate_artifacts.py): the
        # committed file is legacy-allowlisted by name, but every
        # regeneration must be attributable (staticcheck writer gate)
        from _telemetry import telemetry
        out["provenance"] = telemetry().provenance()
        with open(ART, "w") as f:
            json.dump(out, f, indent=1)
        print(f"wrote {ART}", flush=True)
    print(json.dumps(out["contract"]))
    return 0 if (exact_ok and quant_ok and bound_ok and not
                 [s for s in skipped if s not in SKIPPED_BY_DESIGN]) else 1


if __name__ == "__main__":
    sys.exit(main(set(sys.argv[1:]) or None))
