#!/usr/bin/env python
"""Capture the XLA cost & memory attribution record (the
observability PR's acceptance artifact).

One compile per engine — dense, packed, sparse, fused, crdt, log,
txn — acquired through the ONE attribution chokepoint
(utils/compile_cache.load_or_compile via utils/trace.aot_timed)
against a FRESH executable store, so every compile is a forced miss
whose ``xla_compile`` event carries the driver label, the executable
fingerprint, the compile wall, the cache verdict, and XLA's own
cost/memory analysis (explicit nulls where the backend reports none —
record-never-gate).  A re-jitted identical program then re-enters the
chokepoint and must come back a store HIT: executable reuse across
closures, proven in the same ledger.

The packed budget cross-check (the drift gate): a forced >=4-tile
plan runs through the streamed executor with ``measure_memory=True``,
whose measuring compile now routes through the chokepoint too
(label ``scale_stream``) and emits one ``budget_xcheck`` event
(planner/budget.crosscheck_peak) pairing XLA's measured peak bytes
against the planner's predicted closed form — measured <= predicted
or the artifact is red.

Everything lands in ONE run ledger (provenance first line), so the
committed artifact passes tools/validate_artifacts.py's
cost/xprof/attribution provenance gate; tools/cost_report.py renders
it; bench.costs_for_headline() rides it.

    python tools/cost_capture.py [OUT.jsonl]   # default
        artifacts/ledger_cost_r24.jsonl
    python tools/cost_capture.py --smoke       # smaller forced-tile
        leg, .smoke-infixed artifact (hw_refresh convention)

Platform: ambient (the hw_refresh convention) — the committed record
on this container is the CPU structural proof (CPU XLA reports both
cost_analysis and memory_analysis); the same tool at a TPU window
attributes real HBM executables.
"""

import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

ENGINES = ("dense", "packed", "sparse", "fused", "crdt", "log", "txn")

XCHECK_N = 2**16
XCHECK_ROUNDS = 8
SMOKE_XCHECK_N = 2**14
XCHECK_RUMORS = 256     # 8 word planes -> 4 tiles at the forced budget


def _engine_compiles(led, mesh, n_devices):
    """One attributed compile per engine on tiny shapes (the dry-run
    body's constructions, one step each).  Emits a ``cost_case`` event
    per engine (label + plan shape) so tools/cost_report can normalize
    attributed bytes to bytes/node/round."""
    import jax

    from gossip_tpu import config as C
    from gossip_tpu.config import (CrdtConfig, FaultConfig, LogConfig,
                                   ProtocolConfig, RunConfig, TxnConfig)
    from gossip_tpu.parallel.sharded import (init_sharded_state,
                                             make_sharded_si_round)
    from gossip_tpu.parallel.sharded_crdt import (
        init_sharded_crdt_state, make_sharded_crdt_round)
    from gossip_tpu.parallel.sharded_fused import (
        make_plane_mesh, simulate_until_sharded_fused)
    from gossip_tpu.parallel.sharded_log import (
        init_sharded_log_state, make_sharded_log_round)
    from gossip_tpu.parallel.sharded_packed import (
        init_sharded_packed_state, make_sharded_packed_round)
    from gossip_tpu.parallel.sharded_register import (
        init_sharded_reg_state, make_sharded_register_round)
    from gossip_tpu.parallel.sharded_sparse import (
        init_sparse_state, make_sparse_pull_round)
    from gossip_tpu.topology import generators as G
    from gossip_tpu.utils import trace as TR

    run = RunConfig(seed=0)
    fault = FaultConfig(drop_prob=0.05, seed=2)
    n = 16 * n_devices
    topo = G.complete(n)

    def case(label, step, *args, rounds=1, nn=None):
        led.event("cost_case", sync=False, label=label,
                  n=nn if nn is not None else n, rounds=rounds)
        out, compile_s, steady_s, cache = TR.aot_timed(step, *args,
                                                       label=label)
        return cache

    verdicts = {}

    proto = ProtocolConfig(mode=C.PUSH_PULL, fanout=2, rumors=2)
    dstep = jax.jit(make_sharded_si_round(proto, topo, mesh, fault,
                                          run.origin))
    dstate = init_sharded_state(run, proto, topo, mesh)
    verdicts["dense"] = case("dense", dstep, dstate)

    pproto = ProtocolConfig(mode=C.PULL, fanout=1, rumors=40)
    pstep = jax.jit(make_sharded_packed_round(pproto, topo, mesh,
                                              fault))
    pstate = init_sharded_packed_state(run, pproto, topo, mesh)
    verdicts["packed"] = case("packed", pstep, pstate)

    sproto = ProtocolConfig(mode=C.ANTI_ENTROPY, fanout=2, rumors=33,
                            period=2)
    sn = 8 * n_devices * n_devices
    sstep = jax.jit(make_sparse_pull_round(sproto, sn, mesh, fault))
    sstate = init_sparse_state(run, sproto, sn, mesh)
    verdicts["sparse"] = case("sparse", sstep, sstate, nn=sn)

    dproto = ProtocolConfig(mode=C.PULL, fanout=2)
    dcfg = CrdtConfig(kind="gcounter")
    cstep = jax.jit(make_sharded_crdt_round(dcfg, dproto, topo, mesh,
                                            fault, run.origin))
    cstate = init_sharded_crdt_state(run, dcfg, topo, mesh)
    verdicts["crdt"] = case("crdt", cstep, cstate)

    gcfg = LogConfig(keys=4, capacity=8)
    gstep = jax.jit(make_sharded_log_round(gcfg, dproto, topo, mesh,
                                           fault, run.origin))
    gstate = init_sharded_log_state(run, gcfg, topo, mesh)
    verdicts["log"] = case("log", gstep, gstate)

    xcfg = TxnConfig(keys=8, txns=16, zipf_alpha=1.2, hot_key=0.3)
    xstep = jax.jit(make_sharded_register_round(xcfg, dproto, topo,
                                                mesh, fault,
                                                run.origin))
    xstate = init_sharded_reg_state(run, xcfg, topo, mesh)
    verdicts["txn"] = case("txn", xstep, xstate)

    # the fused driver compiles INSIDE simulate_until_sharded_fused —
    # its own maybe_aot_timed sites carry label="fused", so the event
    # stream attributes it with zero plumbing here
    fmesh = make_plane_mesh(n_devices)
    frumors = 32 * n_devices + 7
    led.event("cost_case", sync=False, label="fused", n=128 * 8,
              rounds=2)
    simulate_until_sharded_fused(128 * 8, frumors,
                                 RunConfig(seed=0, max_rounds=2),
                                 fmesh, interpret=True, timing={})

    # salted warm re-entry: a FRESH jit wrapper of the identical dense
    # program lowers to the same HLO, so the chokepoint must come back
    # a store HIT — cross-closure executable reuse, in this ledger
    dstep2 = jax.jit(make_sharded_si_round(proto, topo, mesh, fault,
                                           run.origin))
    verdicts["dense_warm"] = case("dense", dstep2, dstate)
    return verdicts


def _packed_xcheck(n, rounds):
    """The forced >=4-tile streamed run whose measuring compile emits
    the ``budget_xcheck`` drift-gate event (planner/stream routes
    _measure_loop_bytes through the chokepoint + crosscheck_peak)."""
    from gossip_tpu.config import FaultConfig
    from gossip_tpu.planner import budget as PB
    from gossip_tpu.planner.stream import run_at_scale
    fault = FaultConfig(drop_prob=0.02, seed=2)
    dev = PB.forced_device_for_tiles(
        n, rumors=XCHECK_RUMORS, fanout=2, max_rounds=rounds,
        fault=fault, tiles_at_least=4)
    plan = PB.plan_scale(n, rumors=XCHECK_RUMORS, device=dev, fanout=2,
                         max_rounds=rounds, fault=fault,
                         segment_every=max(2, rounds // 2))
    res = run_at_scale(plan, measure_memory=True)
    return plan, res


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    smoke = "--smoke" in argv
    argv = [a for a in argv if a != "--smoke"]
    infix = ".smoke" if smoke else ""
    out_path = (argv[0] if argv else
                os.path.join(REPO, "artifacts",
                             f"ledger_cost_r24{infix}.jsonl"))
    if smoke:
        os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=2"
        ).strip()

    import jax

    from gossip_tpu.utils import compile_cache, telemetry

    n_devices = 2
    led = telemetry.Ledger(out_path)
    prev = telemetry.activate(led)
    t0 = time.perf_counter()
    try:
        led.record_runtime()
        with tempfile.TemporaryDirectory() as cache_dir:
            # a FRESH store: every engine compile is a forced miss
            # whose attribution event carries a real compile wall
            os.environ[compile_cache.ENV_VAR] = cache_dir
            from jax.sharding import Mesh
            mesh = Mesh(jax.devices()[:n_devices], ("nodes",))
            verdicts = _engine_compiles(led, mesh, n_devices)
            plan, res = _packed_xcheck(
                SMOKE_XCHECK_N if smoke else XCHECK_N,
                XCHECK_ROUNDS)

        events = telemetry.load_ledger(led.path, run="last")
        compiles = [e for e in events if e.get("ev") == "xla_compile"]
        xchecks = [e for e in events if e.get("ev") == "budget_xcheck"]
        labels = {e.get("label") for e in compiles}
        gates = {
            "engines_attributed":
                set(ENGINES) <= labels and "scale_stream" in labels,
            "all_events_attributed": bool(compiles) and all(
                e.get("label")
                and e.get("cache") in ("hit", "miss", "disabled")
                for e in compiles),
            "attribution_fields_present": bool(compiles) and all(
                all(f in e for f in compile_cache.ATTRIBUTION_FIELDS)
                for e in compiles),
            "warm_hit": verdicts.get("dense_warm") == "hit",
            "tiles_ge_4": res.tiles >= 4,
            "xcheck_green": bool(xchecks)
                and xchecks[-1].get("ok") is True,
        }
        ok = all(gates.values())
        led.event("cost_record", smoke=smoke,
                  backend=jax.default_backend(),
                  engines=sorted(labels - {None}),
                  compiles=len(compiles),
                  verdicts=verdicts,
                  xcheck_n=plan.n, xcheck_tiles=res.tiles,
                  predicted_peak_device_bytes=
                  plan.predicted_peak_device_bytes,
                  measured_loop_bytes=res.measured_loop_bytes,
                  wall_ms=round((time.perf_counter() - t0) * 1e3, 1),
                  ok=ok, **gates)
        print(json.dumps({"ok": ok, "gates": gates,
                          "engines": sorted(labels - {None}),
                          "compiles": len(compiles),
                          "backend": jax.default_backend(),
                          "ledger": out_path}))
        return 0 if ok else 1
    finally:
        telemetry.activate(prev)
        led.close()


if __name__ == "__main__":
    sys.exit(main())
