#!/usr/bin/env python
"""Render the BENCH_r01..rNN scoreboard trajectory as a markdown table.

The per-round bench records (``BENCH_rNN.json`` at the repo root: the
driver's capture of ``python bench.py`` — cmd, rc, tail, parsed line)
are the only longitudinal record of the headline metric, and until this
tool the trajectory lived ONLY in unrendered JSON: reading how the
number moved across rounds meant opening five files and mentally
joining five schemas (the measurement line grew ``backend``,
``last_tpu`` and ``compile_split`` fields over time).

    python tools/bench_trend.py            # repo-root BENCH_r*.json
    python tools/bench_trend.py DIR        # any directory

One row per record, lexicographic round order.  A CPU-fallback round
renders its own (honest, fallback-tagged) number AND the ``last_tpu``
pointer it carried, so the table shows both what ran and what the
newest committed TPU proof was at that time — the scoreboard-integrity
rule of bench.py's measurement_line: a fallback can hide the live
number but never the proof.  Paste the output into docs/PERF.md
("Bench trajectory").
"""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_records(root=REPO):
    """[(round_tag, parsed_line)] for every BENCH_r*.json in ``root``,
    lexicographic (r01 < r02 < ...) order.  Records whose ``parsed``
    line is missing render as failed rounds rather than vanishing —
    a dark round must stay visible in the trajectory."""
    rows = []
    for name in sorted(os.listdir(root)):
        if not (name.startswith("BENCH_r") and name.endswith(".json")):
            continue
        tag = name[len("BENCH_"):-len(".json")]
        try:
            with open(os.path.join(root, name)) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            rows.append((tag, None))
            continue
        rows.append((tag, rec.get("parsed")))
    return rows


def _human_rate(v):
    if v is None:
        return "—"
    if v >= 1e9:
        return f"{v / 1e9:.2f}B"
    if v >= 1e6:
        return f"{v / 1e6:.1f}M"
    return f"{v:,.0f}"


def render(rows):
    """The trajectory as markdown lines."""
    out = ["| round | backend | node-rounds/s/chip | vs_baseline "
           "| compile cold/warm (s) | last committed TPU proof |",
           "|---|---|---|---|---|---|"]
    for tag, line in rows:
        if not line:
            out.append(f"| {tag} | — | *(record unparsable)* | — | — "
                       "| — |")
            continue
        backend = line.get("backend")
        if backend is None:
            # the r01/r02-era line had no backend FIELD, but the unit
            # string always carried "backend=..." — recover it from
            # there, never from vs_baseline (round 2's wedged-tunnel
            # CPU fallback published vs_baseline 0.21x, the exact
            # masquerade the backend field was added to kill)
            unit = line.get("unit", "")
            if "backend=" in unit:
                backend = unit.split("backend=")[-1].rstrip(")")
        vsb = line.get("vs_baseline")
        split = line.get("compile_split") or {}
        cold, warm = split.get("cold_s"), split.get("warm_s")
        split_s = (f"{cold:.1f} / {warm:.1f}"
                   if cold is not None and warm is not None
                   else f"{cold:.1f} / —" if cold is not None else "—")
        lt = line.get("last_tpu") or {}
        proof = (f"{_human_rate(lt.get('value'))} "
                 f"({lt.get('vs_baseline')}x, `{lt.get('artifact')}`)"
                 if lt.get("value") is not None else "—")
        out.append(
            f"| {tag} | {backend or '—'} "
            f"| {_human_rate(line.get('value'))} "
            f"| {vsb if vsb is not None else '—'} "
            f"| {split_s} | {proof} |")
    return out


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    root = argv[0] if argv else REPO
    rows = load_records(root)
    if not rows:
        print(f"no BENCH_r*.json records in {root}", file=sys.stderr)
        return 1
    print("\n".join(render(rows)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
