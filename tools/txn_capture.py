#!/usr/bin/env python
"""Capture the txn-register convergence + anomaly-verdict record (the
transactions PR's acceptance artifact).

Two legs, one provenance-stamped ledger:

1. **Convergence leg** — the sharded LWW-register driver on the
   4-device pull fabric under ONE mixed nemesis fault program (a
   crash/recover event, a permanent crash, an open partition window,
   and a drop-rate ramp), gating:

   * ``txn_conv == 1.0``: EVERY eventually-alive node's full register
     row (value + timestamp planes) equals the acked-writes LWW
     ground truth (integer-exact full-row equality, divided once on
     the host);
   * the partition STALL is visible: while the window is open, nobody
     holds the global truth (txn_conv < 1 for those rounds);
   * 1-device/4-device trajectory parity BITWISE (the fabric's
     mesh-invariance contract, re-proven on the committed evidence);
   * the truth summary (per-key winners + unpacked (round, owner)
     timestamps) agrees between the mesh and single-device drivers.

2. **Anomaly leg** — the Maelstrom ``txn-rw-register`` workload
   (runtime/maelstrom_harness.run_txn_workload) through a
   harness-injected mid-run partition, gating the weak-isolation
   verdicts: **zero G0** (dirty write: no cycle in the per-key LWW
   version orders), **zero G1a** (aborted read), zero trace defects,
   and cross-node LWW convergence after heal — the totally-available
   isolation claim, checked, not asserted.

Everything lands in one run ledger (utils/telemetry — provenance
first line; the drivers flush their ``round_metrics`` events with the
``txn_conv`` column), so the committed artifact passes
tools/validate_artifacts.py's ``*txn*``/``*register*`` provenance
gate.

    python tools/txn_capture.py [OUT.jsonl]    # default
        artifacts/ledger_txn_r16.jsonl

Runs on the hermetic CPU tier by design (register convergence is
integer arithmetic and the anomaly checker is protocol logic, not a
chip rate).
"""

import asyncio
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N = 64
DEVICES = 4
MAX_ROUNDS = 24
PARTITION_END = 6


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    out_path = (argv[0] if argv else
                os.path.join(REPO, "artifacts",
                             "ledger_txn_r16.jsonl"))
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={DEVICES}"
        ).strip()

    import numpy as np
    from gossip_tpu.config import (ChurnConfig, FaultConfig,
                                   ProtocolConfig, RunConfig,
                                   TxnConfig)
    from gossip_tpu.models.register import simulate_curve_txn
    from gossip_tpu.parallel.sharded import make_mesh
    from gossip_tpu.parallel.sharded_register import (
        simulate_curve_txn_sharded)
    from gossip_tpu.runtime.maelstrom_harness import run_txn_workload
    from gossip_tpu.topology import generators as G
    from gossip_tpu.utils import telemetry

    proto = ProtocolConfig(mode="pull", fanout=2)
    topo = G.complete(N)
    run = RunConfig(seed=0, max_rounds=MAX_ROUNDS, target_coverage=1.0)
    mesh = make_mesh(DEVICES)
    # the mixed fault program: crash/recover, permanent crash, open
    # partition window, drop ramp — every schedule feature at once
    fault = FaultConfig(drop_prob=0.05, seed=1, churn=ChurnConfig(
        events=((3, 2, 5), (7, 1, -1)),
        partitions=((0, PARTITION_END, N // 2),),
        ramp=(1, 4, 0.0, 0.3)))
    cfg = TxnConfig(keys=8, txns=24, zipf_alpha=1.2, hot_key=0.3)

    led = telemetry.Ledger(out_path)
    prev = telemetry.activate(led)
    ok = True
    try:
        led.record_runtime()
        led.event("txn_fault_program",
                  events=[list(e) for e in fault.churn.events],
                  partitions=[list(w) for w in fault.churn.partitions],
                  ramp=list(fault.churn.ramp), drop_prob=fault.drop_prob,
                  n=N, keys=cfg.keys, txns=cfg.txns,
                  zipf_alpha=cfg.zipf_alpha, hot_key=cfg.hot_key,
                  max_rounds=MAX_ROUNDS)
        with led.span("txn:register", keys=cfg.keys):
            conv4, msgs4, fin4, truth4 = simulate_curve_txn_sharded(
                cfg, proto, topo, run, mesh, fault)
            conv1, msgs1, fin1, truth1 = simulate_curve_txn(
                cfg, proto, topo, run, fault)
        parity = bool(
            (np.asarray(conv1) == np.asarray(conv4)).all()
            and (np.asarray(fin1.val)
                 == np.asarray(fin4.val)[:N]).all()
            and truth1 == truth4)
        stalled = bool(all(c < 1.0 for c in conv4[:PARTITION_END]))
        conv_ok = bool(conv4[-1] == 1.0) and parity and stalled
        led.event("txn_scenario",
                  txn_conv_final=float(conv4[-1]),
                  txn_conv_curve=[round(float(c), 6) for c in conv4],
                  truth=truth4,
                  msgs=float(msgs4[-1]),
                  partition_stall_rounds=PARTITION_END,
                  partition_stalled=stalled,
                  mesh_parity_bitwise=parity,
                  devices=DEVICES, ok=conv_ok)

        # anomaly leg: the live workload trace through a mid-run
        # partition, judged by the weak-isolation checker
        with led.span("txn:workload"):
            stats = asyncio.run(run_txn_workload(
                4, ops=16, rate=25.0, latency=0.001,
                partition_mid=True, seed=0))
        anom_ok = bool(stats["invariant_ok"] and stats["g0_ok"]
                       and stats["g1a_ok"] and stats["converged"]
                       and stats["partitioned"])
        led.event("txn_workload",
                  g0=stats["anomalies"]["g0"],
                  g1a=stats["anomalies"]["g1a"],
                  defects=stats["anomalies"]["defects"],
                  g0_ok=stats["g0_ok"], g1a_ok=stats["g1a_ok"],
                  converged=stats["converged"],
                  committed=stats["committed"],
                  aborted=stats["aborted"],
                  indeterminate=stats["indeterminate"],
                  partitioned=stats["partitioned"],
                  invariant_ok=stats["invariant_ok"], ok=anom_ok)
        ok = conv_ok and anom_ok
        led.event("txn_verdict", ok=ok)
    finally:
        telemetry.activate(prev)
        led.close()
    print(json.dumps({"out": out_path, "ok": ok}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
