#!/usr/bin/env python
"""CI/capture entry for the AST invariant analyzer (``gossip_tpu
staticcheck``): run all four checker families over the live tree,
write the provenance-stamped findings ledger, and print one summary
JSON line (the hw_refresh last-stdout-line contract).

    python tools/staticcheck.py                # artifacts/ledger_staticcheck_r19.jsonl
    python tools/staticcheck.py --smoke        # .smoke infixed artifact
    python tools/staticcheck.py --no-ledger    # console-only (pre-commit)

Pure stdlib + the repo's own analysis package — never imports jax, so
this step runs identically on a laptop, a saturated CI host, and a
wedged-tunnel TPU box (it is the one hw_refresh step that cannot be
taken down by the tunnel).  Exit 0 iff the tree is clean against the
suppression baseline (tools/staticcheck_baseline.json); findings print
one per line before the summary.  Gated in tier-1 by
tests/test_staticcheck.py (clean-tree gate + committed-artifact pin).
"""

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ARTIFACT_STEM = "ledger_staticcheck_r19"


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true",
                    help="rehearsal mode: same full analysis (AST "
                         "passes are already single-digit seconds), "
                         ".smoke-infixed artifact")
    ap.add_argument("--ledger", default=None, metavar="PATH",
                    help="findings-ledger path (default: artifacts/"
                         f"{ARTIFACT_STEM}[.smoke].jsonl)")
    ap.add_argument("--no-ledger", action="store_true",
                    help="console-only run, write nothing")
    a = ap.parse_args(argv)

    sys.path.insert(0, REPO)
    try:
        from gossip_tpu.analysis import runner
    finally:
        sys.path.pop(0)

    report = runner.run_tree()
    ledger = None
    if not a.no_ledger:
        infix = ".smoke" if a.smoke else ""
        ledger = a.ledger or os.path.join(
            REPO, "artifacts", f"{ARTIFACT_STEM}{infix}.jsonl")
        runner.write_ledger(report, ledger)
    for f in report.findings:
        print(f.render(), file=sys.stderr)
    counts = report.counts()
    print(json.dumps({
        "verdict": "clean" if report.clean else "dirty",
        "findings": len(report.findings),
        "suppressed": len(report.suppressed),
        "baseline_entries": report.baseline_entries,
        "files_scanned": report.files_scanned,
        "counts": counts,
        **({"ledger": ledger} if ledger else {})}))
    return 0 if report.clean else 1


if __name__ == "__main__":
    sys.exit(main())
