#!/usr/bin/env python
"""A/B the SWIM dissemination lowerings on the real chip.

docs/PERF.md "SWIM-1M cost budget" leaves steady state (~374 ms/round
at 1M nodes) as the remaining lever, and the repo cost model prices its
dominant HBM term — the sorted row gather — at ~7 ns/word x M*S words.
``swim_diss='pack'`` (models/swim.disseminate_max) gathers 8/16-bit
packed transport codes instead, 4x/2x fewer words, bitwise-identical
trajectories (tests/test_swim.py pins the equivalence).  This tool
arbitrates on hardware, exactly like the r04 sort-vs-scatter A/B
(artifacts/swim_ab_r04.json) whose verdict made sort the default:

  - runs the exact BASELINE SWIM-1M shape through the run CLI once per
    impl (fresh per-impl compile-cache dir: compile_s stays honest),
  - asserts the trajectories match (rounds / coverage / msgs equal —
    anything else means the lowering is NOT pure and must not ship),
  - writes artifacts/swim_diss_ab_r05.json with walls, steady split,
    and a verdict line.

Run only when the tunnel is healthy (tools/tunnel_watchdog.py probes
first).  ``--smoke`` rehearses the plumbing at CPU scale (n=20k, no
TPU) writing a ``.smoke``-infixed artifact, repo convention.

    python tools/swim_diss_ab.py                 # sort (control) vs pack
    python tools/swim_diss_ab.py --impls scatter sort pack
    python tools/swim_diss_ab.py --smoke
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
try:
    from _bench import hermetic_cpu_env as _hermetic_cpu_env  # noqa: E402
finally:
    sys.path.pop(0)


PROBE_TIMEOUT_S = 120
POST_FAILURE_PROBE_S = 60
DEFAULT_RUN_TIMEOUT_S = 900


def worst_case_budget_s(n_impls: int = 2,
                        run_timeout_s: int = DEFAULT_RUN_TIMEOUT_S) -> int:
    """Upper bound on a full A/B run (probe + every run at its full
    timeout + the post-failure disambiguation probe), exported so
    tools/hw_refresh.py derives its step budget from the same constants
    this file's loops use — a parent timeout below this can kill us
    before our own group-kill fires, orphaning a live TPU client."""
    return (PROBE_TIMEOUT_S + n_impls * run_timeout_s
            + POST_FAILURE_PROBE_S)


class WedgeTimeout(RuntimeError):
    """A run blew its subprocess budget — the tunnel-wedge signature.
    Transient, not a verdict: main() maps this to exit code 2, the
    capture tools' convention for "retry at a later healthy window"
    (tools/tunnel_watchdog.py --cmd retries 2, gives up on 1)."""


class CliFailed(RuntimeError):
    """The run CLI exited nonzero.  Ambiguous: a wedged tunnel can fail
    FAST at init (bench.py's 'fast init failure' symptom), or the
    candidate lowering can genuinely crash.  main() disambiguates by
    re-probing the tunnel — probe dead -> exit 2 (transient), probe
    alive -> exit 1 (deterministic; do not retry)."""


def probe(timeout_s: int = PROBE_TIMEOUT_S) -> bool:
    """Cheap tunnel probe (the wedge signature is a hang, so a timeout
    means NO — tools/tunnel_watchdog.py's contract).  Skipped in smoke
    mode."""
    try:
        p = subprocess.run(
            [sys.executable, "-c", "import jax; print(jax.devices())"],
            capture_output=True, text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return False
    return p.returncode == 0

BASE_ARGS = ["--mode", "swim", "--family", "power_law", "--k", "3",
             "--degree-cap", "256", "--fanout", "2", "--swim-subjects", "8",
             "--swim-proxies", "3", "--swim-suspect-rounds", "24",
             "--max-rounds", "80"]


def run_one(impl: str, n: int, timeout_s: int, smoke: bool) -> dict:
    cmd = [sys.executable, "-m", "gossip_tpu", "run", "--n", str(n),
           *BASE_ARGS, "--swim-diss", impl]
    env = _hermetic_cpu_env() if smoke else dict(os.environ)
    with tempfile.TemporaryDirectory(prefix=f"swimab-{impl}-") as cache:
        cmd += ["--compile-cache", cache]   # per-impl dir: cold, honest
        t0 = time.time()
        # own process group + group kill on timeout: a half-killed TPU
        # client wedges the single-client tunnel (watchdog contract)
        p = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                             stderr=subprocess.PIPE, text=True, cwd=REPO,
                             env=env, start_new_session=True)
        try:
            stdout, stderr = p.communicate(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(os.getpgid(p.pid), signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
            p.communicate()
            raise WedgeTimeout(
                f"{impl}: run timed out after {timeout_s} s — tunnel "
                "wedge signature; aborting (retry at the next healthy "
                "window, e.g. tools/tunnel_watchdog.py --cmd)")
    if p.returncode != 0:
        raise CliFailed(f"{impl}: run CLI failed rc={p.returncode}\n"
                        f"{stderr[-2000:]}")
    out = None
    for line in stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                cand = json.loads(line)
            except json.JSONDecodeError:
                continue
            if "wall_s" in cand:
                out = cand
    if out is None:
        raise RuntimeError(f"{impl}: no result JSON on stdout\n"
                           f"{stdout[-2000:]}")
    meta = out.get("meta") or {}
    return {"swim_diss": impl,
            "wall_s": out["wall_s"],
            "compile_s": meta.get("compile_s"),
            "steady_wall_s": meta.get("steady_wall_s"),
            "rounds": out["rounds"],
            "coverage": out["coverage"],
            "msgs": out["msgs"],
            "subprocess_wall_s": round(time.time() - t0, 1)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--impls", nargs="+", default=["sort", "pack"])
    ap.add_argument("--n", type=int, default=1_000_000)
    ap.add_argument("--timeout", type=int, default=DEFAULT_RUN_TIMEOUT_S,
                    help="per-run subprocess timeout (s)")
    ap.add_argument("--smoke", action="store_true",
                    help="CPU-scale rehearsal (n=20k, JAX_PLATFORMS=cpu)")
    a = ap.parse_args()
    if not a.smoke and not probe():
        print("tunnel probe failed (wedge signature) — not burning the "
              "per-run budget; retry at the next healthy window",
              file=sys.stderr)
        return 2
    n = 20_000 if a.smoke else a.n
    infix = ".smoke" if a.smoke else ""
    art = os.path.join(REPO, "artifacts", f"swim_diss_ab_r05{infix}.json")

    rows = []
    for impl in a.impls:
        try:
            row = run_one(impl, n, a.timeout, a.smoke)
        except WedgeTimeout as e:
            print(str(e), file=sys.stderr)
            return 2          # transient: the watchdog retries rc 2
        except CliFailed as e:
            print(str(e), file=sys.stderr)
            if not a.smoke and not probe(timeout_s=POST_FAILURE_PROBE_S):
                print("post-failure probe dead — wedge-shaped fast init "
                      "failure; retry at the next healthy window",
                      file=sys.stderr)
                return 2      # transient
            return 1          # deterministic CLI failure: a real bug
        print(json.dumps(row), flush=True)
        rows.append(row)

    traj = {(r["rounds"], r["coverage"], r["msgs"]) for r in rows}
    identical = len(traj) == 1
    verdict = winner = None
    if identical and len(rows) >= 2:
        # winner = min steady over ALL rows (control included): a
        # candidate that regresses must lose to the control, and the
        # artifact's field is THE arbitration consumers read
        # (hw_refresh.swim_diss_winner) — one definition, one file
        ctl, best = rows[0], min(rows, key=lambda r: r["steady_wall_s"])
        winner = best["swim_diss"]
        verdict = (f"winner {winner}: steady {ctl['steady_wall_s']:.1f}"
                   f" -> {best['steady_wall_s']:.1f} s, compile "
                   f"{ctl['compile_s']:.1f} -> {best['compile_s']:.1f} s "
                   f"vs {ctl['swim_diss']} control")
    from _telemetry import telemetry
    doc = {
        # the one artifact schema (run_id/git_commit/captured —
        # tools/validate_artifacts.py): regenerations must be
        # attributable even though the committed file is
        # legacy-allowlisted by name (staticcheck writer gate)
        "provenance": telemetry().provenance(),
        "what": ("A/B of ProtocolConfig.swim_diss lowerings on the "
                 "BASELINE SWIM-1M shape; identical trajectories required "
                 "(rounds/coverage/msgs) per models/swim.disseminate_max"),
        "command": ("python -m gossip_tpu run --n %d %s "
                    "--swim-diss {%s} --compile-cache FRESH_DIR"
                    % (n, " ".join(BASE_ARGS), "|".join(a.impls))),
        "rows": rows,
        "trajectories_identical": identical,
        "winner": winner,
        "verdict": verdict,
    }
    with open(art, "w") as f:
        json.dump(doc, f, indent=1)
    print(f"wrote {art}", file=sys.stderr)
    if not identical:
        print("TRAJECTORY MISMATCH — the candidate lowering is not pure; "
              "do not change the default", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
