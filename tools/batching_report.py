#!/usr/bin/env python
"""Record the Maelstrom interval-batching efficiency artifact.

Runs the broadcast workload twice through `gossip-tpu maelstrom-check`
— the reference-shaped immediate fan-out and the interval-batched
variant (VERDICT r3 item 7) — on the same seeded 5-node line at a high
op rate, and writes ``artifacts/maelstrom_batching_r05.json`` with both
reports plus the Glomers-style gates the batched run is held to
(msgs-per-op <= 12 on a 5-node line at 20 values; the checker's
eventual-delivery invariant on both).  Routing counts are measured from
real node processes, so exact numbers vary run to run by a message or
two; the CONTRACT (batched strictly below immediate, both invariants
green, gates met) is what the exit code enforces.

    python tools/batching_report.py
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ART = os.path.join(REPO, "artifacts", "maelstrom_batching_r05.json")


def check(*extra, n=5, ops=20):
    cmd = [sys.executable, "-m", "gossip_tpu", "maelstrom-check",
           "--n", str(n), "--ops", str(ops), "--rate", "200",
           "--seed", "4", *extra]
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("PALLAS_AXON_POOL_IPS", None)   # node procs are jax-free
    p = subprocess.run(cmd, capture_output=True, text=True, timeout=300,
                       cwd=REPO, env=env)
    if not p.stdout.strip():
        # crashed before printing its report: surface the node's error,
        # not an IndexError in this tool (parity_matrix.run_cell pattern)
        raise RuntimeError("maelstrom-check produced no report; stderr: "
                           + (p.stderr or "")[-300:])
    rep = json.loads(p.stdout.strip().splitlines()[-1])
    rep["exit_code"] = p.returncode
    return rep


def main():
    immediate = check()
    batched = check("--gossip-interval", "0.05",
                    "--assert-msgs-per-op", "12",
                    "--assert-latency-ms", "2000")
    ok = (immediate["invariant_ok"] and immediate["exit_code"] == 0
          and batched["invariant_ok"] and batched["exit_code"] == 0
          and batched["msgs_per_op"] < immediate["msgs_per_op"])

    # Composition matrix (round 4): the SAME two relay variants through
    # the native C++ poll() router, and both variants under a mid-run
    # partition window on each router — the checker's eventual-delivery
    # invariant must hold in every cell (batching must not break
    # partition healing, on either harness).  No msgs-per-op gate in the
    # partition cells: retries during the cut legitimately raise it.
    matrix = {}
    gates = ("--assert-msgs-per-op", "12", "--assert-latency-ms", "2000")
    for router in ("python", "native"):
        for label, extra in (("immediate", ()),
                             ("batched", ("--gossip-interval", "0.05"))):
            for part, pextra in (("", ()), ("+partition", ("--partition",))):
                if router == "python" and not part:
                    # reuse the two baseline runs above (gates included
                    # on the batched one)
                    rep = immediate if label == "immediate" else batched
                else:
                    # batched non-partition cells carry the same gates
                    # as the baseline; partition cells don't (retries
                    # during the cut legitimately raise msgs-per-op)
                    cell_gates = (gates if label == "batched" and not part
                                  else ())
                    rep = check("--router", router, *extra, *pextra,
                                *cell_gates)
                cell = f"{router}/{label}{part}"
                matrix[cell] = rep
                ok = ok and rep["invariant_ok"] and rep["exit_code"] == 0

    # Glomers "broadcast efficiency" scale: the spec's own 25-node grid
    # at its published msgs-per-op budget (< 30).  One batched cell —
    # the 5-node line above carries the fine-grained comparisons.  The
    # 300 ms interval is the arbitrated setting (measured here:
    # immediate 112 msgs/op, 50 ms -> 63, 150 ms -> 33, 300 ms -> ~20
    # at ~5 ms max op latency, far under the 2 s gate).
    glomers = check("--topology", "grid",
                    "--gossip-interval", "0.3",
                    "--assert-msgs-per-op", "30",
                    "--assert-latency-ms", "2000", n=25, ops=40)
    matrix["python/batched-25-grid"] = glomers
    ok = ok and glomers["invariant_ok"] and glomers["exit_code"] == 0

    out = {
        "what": "Maelstrom broadcast workload, immediate vs "
                "interval-batched relay (VERDICT r3 item 7): same seeded "
                "5-node line, 20 values at 200 ops/s, both through the "
                "real-process asyncio harness.  The batched node "
                "accumulates values per neighbor and flushes one gossip "
                "RPC per neighbor per 50 ms tick; the gates "
                "(msgs_per_op <= 12, max op latency <= 2 s) are "
                "enforced by maelstrom-check's exit code.  The round-4 "
                "matrix re-runs both variants through the native C++ "
                "router and under a partition window on each router; "
                "every cell must keep the eventual-delivery invariant.",
        "immediate": immediate,
        "batched": batched,
        "matrix": {cell: {k: rep[k] for k in
                          ("msgs_per_op", "invariant_ok", "partitioned",
                           "exit_code") if k in rep}
                   for cell, rep in matrix.items()},
        "reduction_factor": round(immediate["msgs_per_op"]
                                  / max(batched["msgs_per_op"], 1e-9), 2),
        "contract_ok": ok,
    }
    with open(ART, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"reduction_factor": out["reduction_factor"],
                      "immediate_msgs_per_op": immediate["msgs_per_op"],
                      "batched_msgs_per_op": batched["msgs_per_op"],
                      "matrix_cells": len(matrix),
                      "contract_ok": ok}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
