#!/usr/bin/env python
"""Batching evidence, both layers: render the serving-layer ``batch``
telemetry, and record the Maelstrom interval-batching artifact.

**Serving render** (the admission-batching PR): ``--ledger PATH``
renders a run ledger's per-tick ``batch`` events (rpc/batcher schema —
queue depth, batch size, wait/run walls, compile verdict) plus the
load-harness ``load_leg``/``serving_gate`` rows into the markdown
section tools/telemetry_report.py embeds as "Serving batches"
(:func:`render_serving_section` is the ONE implementation for both
tools; contract-tested against the committed
artifacts/ledger_serving_r14.jsonl record).

    python tools/batching_report.py --ledger artifacts/ledger_serving_r14.jsonl

**Maelstrom capture** (the legacy default, VERDICT r3 item 7): runs the
broadcast workload twice through `gossip-tpu maelstrom-check` — the
reference-shaped immediate fan-out and the interval-batched variant —
on the same seeded 5-node line at a high op rate, and writes
``artifacts/maelstrom_batching_r05.json`` with both reports plus the
Glomers-style gates the batched run is held to (msgs-per-op <= 12 on a
5-node line at 20 values; the checker's eventual-delivery invariant on
both).  Routing counts are measured from real node processes, so exact
numbers vary run to run by a message or two; the CONTRACT (batched
strictly below immediate, both invariants green, gates met) is what
the exit code enforces.

    python tools/batching_report.py            # maelstrom capture
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ART = os.path.join(REPO, "artifacts", "maelstrom_batching_r05.json")


# -- serving-layer batch telemetry render -------------------------------

def batch_rows(events):
    """The run's per-tick ``batch`` events (rpc/batcher schema), in
    order."""
    return [e for e in events if e.get("ev") == "batch"]


def _hist(values, buckets):
    """``[(label, count)]`` text histogram rows over inclusive bucket
    upper bounds (the last bucket is open-ended)."""
    rows = []
    lo = None
    for hi in buckets:
        n = sum(1 for v in values
                if (lo is None or v > lo) and v <= hi)
        rows.append((f"<= {hi:g}" if lo is None else f"{lo:g}..{hi:g}",
                     n))
        lo = hi
    rows.append((f"> {lo:g}", sum(1 for v in values if v > lo)))
    return rows


def _bar(n, total, width=24):
    return "#" * (0 if total == 0 else max(1, round(width * n / total))
                  if n else 0)


def render_serving_section(events):
    """The "Serving batches" markdown section for one run's serving
    telemetry — per-tick batch stats (queue-depth / batch-size / wait
    and run-wall histograms, compile verdicts), the load-harness leg
    summaries, and the gate verdict.  Returns [] when the run carries
    no ``batch`` events (non-serving ledgers) — the embedding report
    (tools/telemetry_report.py) then omits the section entirely."""
    rows = batch_rows(events)
    if not rows:
        return []
    sys.path.insert(0, REPO)
    try:
        from gossip_tpu.utils.telemetry import percentile
    finally:
        sys.path.pop(0)
    out = ["## Serving batches (admission batcher, rpc/batcher)", ""]
    sizes = [r.get("batch_size", 0) for r in rows]
    depths = [r.get("queue_depth", 0) for r in rows]
    waits = [r.get("wait_ms_p50", 0.0) for r in rows]
    runs = [r.get("run_ms", 0.0) for r in rows]
    verdicts = {}
    for r in rows:
        verdicts[r.get("cache")] = verdicts.get(r.get("cache"), 0) + 1
    out.append(f"- {len(rows)} batch tick(s); "
               f"{sum(sizes)} request lane(s) served; compile "
               "verdicts: " + ", ".join(
                   f"{k}={v}" for k, v in sorted(verdicts.items(),
                                                 key=lambda kv:
                                                 str(kv[0]))))
    out.append(f"- batch size p50/max: "
               f"{percentile(sizes, 0.5):g}/{max(sizes):g}; "
               f"queue depth p50/max: "
               f"{percentile(depths, 0.5):g}/{max(depths):g}")
    out.append(f"- per-tick wait p50 of p50s {percentile(waits, 0.5):.1f}"
               f" ms; run wall p50/p95 {percentile(runs, 0.5):.1f}/"
               f"{percentile(runs, 0.95):.1f} ms")
    out.append("")
    for title, vals, buckets in (
            ("batch size", sizes, (1, 2, 4, 8, 16, 32, 64)),
            ("queue depth at drain", depths, (1, 4, 16, 64, 256)),
            ("run wall (ms)", runs, (5, 20, 50, 200, 1000))):
        out.append(f"### {title} histogram")
        out.append("")
        out.append("| bucket | ticks | |")
        out.append("|---|---|---|")
        total = len(vals)
        for label, n in _hist(vals, buckets):
            out.append(f"| {label} | {n} | `{_bar(n, total)}` |")
        out.append("")
    legs = [e for e in events if e.get("ev") == "load_leg"]
    if legs:
        out.append("### Load-harness legs")
        out.append("")
        out.append("| leg | requests | workers | rps | p50 ms | p95 ms "
                   "| p99 ms | errors |")
        out.append("|---|---|---|---|---|---|---|---|")
        for e in legs:
            out.append(f"| {e.get('leg')} | {e.get('requests')} "
                       f"| {e.get('workers')} | {e.get('rps')} "
                       f"| {e.get('p50_ms')} | {e.get('p95_ms')} "
                       f"| {e.get('p99_ms')} | {e.get('errors')} |")
        out.append("")
    gates = [e for e in events if e.get("ev") == "serving_gate"]
    if gates:
        g = gates[-1]
        verdict = "**green**" if g.get("ok") else "**TRIPPED**"
        out.append(f"Serving gate: {verdict} — throughput ratio "
                   f"{g.get('throughput_ratio')}x "
                   f"(>= {g.get('min_ratio')}x), bitwise_equal="
                   f"{g.get('bitwise_equal')}, steady_all_warm="
                   f"{g.get('steady_all_warm')} "
                   f"({g.get('measure_compiles')} compiles in the "
                   "measured window).")
        out.append("")
    return out


def render_serving_ledger(path, run="last"):
    """Standalone render of a serving ledger (--ledger CLI mode)."""
    sys.path.insert(0, REPO)
    try:
        from gossip_tpu.utils.telemetry import load_ledger
    finally:
        sys.path.pop(0)
    events = load_ledger(path, run=run)
    lines = render_serving_section(events)
    if not lines:
        return (f"no `batch` events in {path} (run {run!r}) — not a "
                "serving ledger?")
    return "\n".join([f"# Serving report — {os.path.basename(path)}",
                      ""] + lines)


def check(*extra, n=5, ops=20):
    cmd = [sys.executable, "-m", "gossip_tpu", "maelstrom-check",
           "--n", str(n), "--ops", str(ops), "--rate", "200",
           "--seed", "4", *extra]
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("PALLAS_AXON_POOL_IPS", None)   # node procs are jax-free
    p = subprocess.run(cmd, capture_output=True, text=True, timeout=300,
                       cwd=REPO, env=env)
    if not p.stdout.strip():
        # crashed before printing its report: surface the node's error,
        # not an IndexError in this tool (parity_matrix.run_cell pattern)
        raise RuntimeError("maelstrom-check produced no report; stderr: "
                           + (p.stderr or "")[-300:])
    rep = json.loads(p.stdout.strip().splitlines()[-1])
    rep["exit_code"] = p.returncode
    return rep


def main(argv=None):
    import argparse
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--ledger", default=None,
                    help="render a serving ledger's batch telemetry "
                         "instead of running the Maelstrom capture")
    ap.add_argument("--run", default="last",
                    help="run id within --ledger (default newest)")
    args = ap.parse_args(argv)
    if args.ledger:
        print(render_serving_ledger(args.ledger, run=args.run))
        return 0
    immediate = check()
    batched = check("--gossip-interval", "0.05",
                    "--assert-msgs-per-op", "12",
                    "--assert-latency-ms", "2000")
    ok = (immediate["invariant_ok"] and immediate["exit_code"] == 0
          and batched["invariant_ok"] and batched["exit_code"] == 0
          and batched["msgs_per_op"] < immediate["msgs_per_op"])

    # Composition matrix (round 4): the SAME two relay variants through
    # the native C++ poll() router, and both variants under a mid-run
    # partition window on each router — the checker's eventual-delivery
    # invariant must hold in every cell (batching must not break
    # partition healing, on either harness).  No msgs-per-op gate in the
    # partition cells: retries during the cut legitimately raise it.
    matrix = {}
    gates = ("--assert-msgs-per-op", "12", "--assert-latency-ms", "2000")
    for router in ("python", "native"):
        for label, extra in (("immediate", ()),
                             ("batched", ("--gossip-interval", "0.05"))):
            for part, pextra in (("", ()), ("+partition", ("--partition",))):
                if router == "python" and not part:
                    # reuse the two baseline runs above (gates included
                    # on the batched one)
                    rep = immediate if label == "immediate" else batched
                else:
                    # batched non-partition cells carry the same gates
                    # as the baseline; partition cells don't (retries
                    # during the cut legitimately raise msgs-per-op)
                    cell_gates = (gates if label == "batched" and not part
                                  else ())
                    rep = check("--router", router, *extra, *pextra,
                                *cell_gates)
                cell = f"{router}/{label}{part}"
                matrix[cell] = rep
                ok = ok and rep["invariant_ok"] and rep["exit_code"] == 0

    # Glomers "broadcast efficiency" scale: the spec's own 25-node grid
    # at its published msgs-per-op budget (< 30).  One batched cell —
    # the 5-node line above carries the fine-grained comparisons.  The
    # 300 ms interval is the arbitrated setting (measured here:
    # immediate 112 msgs/op, 50 ms -> 63, 150 ms -> 33, 300 ms -> ~20
    # at ~5 ms max op latency, far under the 2 s gate).
    glomers = check("--topology", "grid",
                    "--gossip-interval", "0.3",
                    "--assert-msgs-per-op", "30",
                    "--assert-latency-ms", "2000", n=25, ops=40)
    matrix["python/batched-25-grid"] = glomers
    ok = ok and glomers["invariant_ok"] and glomers["exit_code"] == 0

    from _telemetry import telemetry
    out = {
        # the one artifact schema (run_id/git_commit/captured —
        # tools/validate_artifacts.py): the committed file rides the
        # legacy allowlist by NAME, but every regeneration must be
        # attributable (the staticcheck artifact-writer-provenance gate)
        "provenance": telemetry().provenance(),
        "what": "Maelstrom broadcast workload, immediate vs "
                "interval-batched relay (VERDICT r3 item 7): same seeded "
                "5-node line, 20 values at 200 ops/s, both through the "
                "real-process asyncio harness.  The batched node "
                "accumulates values per neighbor and flushes one gossip "
                "RPC per neighbor per 50 ms tick; the gates "
                "(msgs_per_op <= 12, max op latency <= 2 s) are "
                "enforced by maelstrom-check's exit code.  The round-4 "
                "matrix re-runs both variants through the native C++ "
                "router and under a partition window on each router; "
                "every cell must keep the eventual-delivery invariant.",
        "immediate": immediate,
        "batched": batched,
        "matrix": {cell: {k: rep[k] for k in
                          ("msgs_per_op", "invariant_ok", "partitioned",
                           "exit_code") if k in rep}
                   for cell, rep in matrix.items()},
        "reduction_factor": round(immediate["msgs_per_op"]
                                  / max(batched["msgs_per_op"], 1e-9), 2),
        "contract_ok": ok,
    }
    with open(ART, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"reduction_factor": out["reduction_factor"],
                      "immediate_msgs_per_op": immediate["msgs_per_op"],
                      "batched_msgs_per_op": batched["msgs_per_op"],
                      "matrix_cells": len(matrix),
                      "contract_ok": ok}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
