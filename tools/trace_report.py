#!/usr/bin/env python
"""Cross-ledger request-trace join: per-request waterfalls + p99
exemplars from trace-bearing run ledgers (docs/OBSERVABILITY.md
"Request tracing").

A traced request leaves TWO ``request_trace`` halves — the router's
(``source="router"``: proxy_ms, retries, deadline_consumed) and the
serving replica's (``source="replica"``: queue_wait_ms, batch_run_ms)
— plus ``dispatch_attempt`` / ``trace_admit`` / ``failover`` spans,
all correlated by the one ``trace_id`` the client minted
(rpc/sidecar.TRACE_KEY).  Those halves land in DIFFERENT writers'
ledgers (router process vs replica subprocess) unless the capture
pointed everyone at one shared file, so this tool joins across any
number of ledger paths and across run ids: a trace is a cross-process
object, a run is not.

    python tools/trace_report.py LEDGER.jsonl [MORE.jsonl ...]
    python tools/trace_report.py ... --json          # machine summary
    python tools/trace_report.py ... --trace TID     # one waterfall

The committed p99 was a number nobody could decompose (20.2 s at 2048
connections, ledger_meshserve_r21.jsonl); the exemplar table here is
the decomposition: the ACTUAL slowest traces, each attributed to its
dominant leg (queue wait vs batch run vs routing/failover overhead).
Embedded in tools/telemetry_report.py via :func:`render_trace_section`
and run by tools/load_harness.py after its serving legs.
"""

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _telemetry():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    try:
        from _telemetry import telemetry
    finally:
        sys.path.pop(0)
    return telemetry()


def load_events(paths):
    """Every event from every ledger, in path-then-file order — no run
    filter: the join key is trace_id, and one trace's events span the
    router's run, each replica's run, and the capture parent's run."""
    tel = _telemetry()
    events = []
    for p in paths:
        events.extend(tel.load_ledger(p))
    return events


def join_traces(events):
    """{trace_id: joined record} over every trace-bearing event (the
    request_trace halves, the attempt/admit/failover spans, and the
    megabatch ``batch`` events' member links)."""
    traces = {}

    def rec(tid):
        return traces.setdefault(tid, {
            "trace_id": tid, "attempts": 0, "failovers": 0,
            "admits": 0, "client_retries": 0, "expired": False,
            "router": None, "replica_halves": [], "ticks": []})

    for e in events:
        ev = e.get("ev")
        if ev == "batch":
            for tid in e.get("trace_ids") or ():
                rec(tid)["ticks"].append(e.get("tick"))
            continue
        tid = e.get("trace_id")
        if tid is None:
            continue
        r = rec(tid)
        if ev == "dispatch_attempt":
            r["attempts"] += 1
        elif ev == "failover":
            r["failovers"] += 1
        elif ev == "trace_admit":
            r["admits"] += 1
        elif ev == "rpc_retry":
            r["client_retries"] += 1
        elif ev == "deadline_exceeded":
            r["expired"] = True
        elif ev == "request_trace":
            if e.get("source") == "router":
                r["router"] = e
            else:
                r["replica_halves"].append(e)
    return traces


def waterfall(joined):
    """One joined trace flattened to the per-request waterfall row.
    ``complete`` = both halves present (the acceptance criterion of the
    r22 capture: every acked request must be complete).  A replayed
    request can leave one replica half per completed attempt; the LAST
    one is the half whose reply the client actually received (the
    failover replay runs after the dead replica's attempt)."""
    ro = joined["router"]
    rep = joined["replica_halves"][-1] if joined["replica_halves"] \
        else None
    row = {"trace_id": joined["trace_id"],
           "complete": ro is not None and rep is not None,
           "attempts": joined["attempts"],
           "failovers": joined["failovers"],
           "client_retries": joined["client_retries"],
           "expired": joined["expired"],
           "ticks": sorted(set(joined["ticks"]))}
    if ro is not None:
        row.update(method=ro.get("method"), replica=ro.get("replica"),
                   proxy_ms=ro.get("proxy_ms"),
                   retries=ro.get("retries"),
                   deadline_consumed=ro.get("deadline_consumed"))
    if rep is not None:
        row.update(req_kind=rep.get("req_kind"),
                   batched=rep.get("batched"),
                   queue_wait_ms=rep.get("queue_wait_ms"),
                   batch_run_ms=rep.get("batch_run_ms"),
                   cache=rep.get("cache"), tick=rep.get("tick"),
                   replica_halves=len(joined["replica_halves"]))
    if ro is not None and rep is not None:
        # routing overhead: what the proxy wall holds beyond the
        # replica's queue+run (network, failover retries, serialization)
        row["overhead_ms"] = round(
            (ro.get("proxy_ms") or 0.0)
            - (rep.get("queue_wait_ms") or 0.0)
            - (rep.get("batch_run_ms") or 0.0), 1)
    return row


def waterfalls(events):
    """Every joined trace as a waterfall row, slowest last."""
    rows = [waterfall(j) for j in join_traces(events).values()]
    rows.sort(key=_wall)
    return rows


def _wall(row):
    """One end-to-end wall per trace: the router's proxy view when
    present (what the client experienced), else the replica's
    queue+run (a replica-only ledger still ranks)."""
    if row.get("proxy_ms") is not None:
        return float(row["proxy_ms"])
    return float(row.get("queue_wait_ms") or 0.0) \
        + float(row.get("batch_run_ms") or 0.0)


def _dominant_leg(row):
    legs = {"queue_wait": row.get("queue_wait_ms") or 0.0,
            "batch_run": row.get("batch_run_ms") or 0.0,
            "routing_overhead": row.get("overhead_ms") or 0.0}
    if not any(legs.values()):
        return "unknown"
    return max(legs, key=lambda k: legs[k])


def exemplars(rows, k=5):
    """The p99 exemplar contract: the ACTUAL k slowest traces (not a
    percentile abstraction), each carrying its full waterfall and the
    leg that dominates it — the attribution the committed tail-latency
    number was missing."""
    out = []
    for row in rows[-k:][::-1]:
        out.append({**row, "wall_ms": round(_wall(row), 1),
                    "dominant_leg": _dominant_leg(row)})
    return out


def summarize(rows):
    """Machine summary of one waterfall set (the --json document and
    the capture tools' assertion surface)."""
    tel = _telemetry()
    pct = tel.percentile
    walls = [_wall(r) for r in rows]
    qw = [r["queue_wait_ms"] for r in rows
          if r.get("queue_wait_ms") is not None]
    br = [r["batch_run_ms"] for r in rows
          if r.get("batch_run_ms") is not None]
    return {
        "traces": len(rows),
        "complete": sum(1 for r in rows if r["complete"]),
        "incomplete": sum(1 for r in rows if not r["complete"]),
        "replayed": sum(1 for r in rows if (r.get("retries") or 0) > 0
                        or r["failovers"] > 0),
        "expired": sum(1 for r in rows if r["expired"]),
        "wall_ms": {"p50": round(pct(walls, 0.50), 1),
                    "p95": round(pct(walls, 0.95), 1),
                    "p99": round(pct(walls, 0.99), 1)},
        "queue_wait_ms": {"p50": round(pct(qw, 0.50), 1),
                          "p99": round(pct(qw, 0.99), 1)},
        "batch_run_ms": {"p50": round(pct(br, 0.50), 1),
                         "p99": round(pct(br, 0.99), 1)},
    }


def render_trace_section(events, k=5):
    """The "Request traces" markdown section for one event set, [] when
    it carries no traces — the same embed contract as
    batching_report.render_serving_section, so telemetry_report omits
    the section on untraced ledgers."""
    rows = waterfalls(events)
    if not rows:
        return []
    s = summarize(rows)
    out = ["## Request traces (trace_id join, tools/trace_report.py)",
           ""]
    out.append(f"- {s['traces']} trace(s): {s['complete']} complete "
               f"waterfall(s), {s['incomplete']} incomplete, "
               f"{s['replayed']} failover-replayed, "
               f"{s['expired']} expired")
    out.append(f"- end-to-end wall ms p50/p95/p99: "
               f"{s['wall_ms']['p50']} / {s['wall_ms']['p95']} / "
               f"{s['wall_ms']['p99']}; queue wait p50/p99: "
               f"{s['queue_wait_ms']['p50']} / "
               f"{s['queue_wait_ms']['p99']}; batch run p50/p99: "
               f"{s['batch_run_ms']['p50']} / "
               f"{s['batch_run_ms']['p99']}")
    out.append("")
    out.append("### p99 exemplars (the actual slowest traces, "
               "attributed)")
    out.append("")
    out.append("| trace_id | wall_ms | queue_wait | batch_run | "
               "overhead | retries | replica | dominant leg |")
    out.append("|---|---|---|---|---|---|---|---|")
    for x in exemplars(rows, k=k):
        out.append(
            f"| `{x['trace_id']}` | {x['wall_ms']} "
            f"| {x.get('queue_wait_ms', '-')} "
            f"| {x.get('batch_run_ms', '-')} "
            f"| {x.get('overhead_ms', '-')} "
            f"| {x.get('retries', x['failovers'])} "
            f"| {x.get('replica', '-')} | {x['dominant_leg']} |")
    out.append("")
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("ledgers", nargs="+",
                    help="one or more telemetry JSONL ledgers (router "
                         "+ replica files join across paths)")
    ap.add_argument("--json", action="store_true",
                    help="print the machine summary (+ exemplars) as "
                         "one JSON document instead of markdown")
    ap.add_argument("--trace", default=None, metavar="TID",
                    help="print one trace's full waterfall + raw "
                         "events (the load_ledger trace_id= filter)")
    ap.add_argument("-k", "--exemplars", type=int, default=5,
                    help="exemplar count in the table (default 5)")
    ap.add_argument("-o", "--out", default=None,
                    help="write output here instead of stdout")
    args = ap.parse_args(argv)

    if args.trace is not None:
        tel = _telemetry()
        evs = []
        for p in args.ledgers:
            evs.extend(tel.load_ledger(p, trace_id=args.trace))
        joined = join_traces(evs)
        if args.trace not in joined:
            print(f"no events for trace {args.trace!r}",
                  file=sys.stderr)
            return 1
        doc = json.dumps({"waterfall": waterfall(joined[args.trace]),
                          "events": evs}, indent=1)
    else:
        events = load_events(args.ledgers)
        rows = waterfalls(events)
        if not rows:
            print("no request_trace events in "
                  + ", ".join(args.ledgers), file=sys.stderr)
            return 1
        if args.json:
            doc = json.dumps({"summary": summarize(rows),
                              "exemplars": exemplars(
                                  rows, k=args.exemplars)})
        else:
            doc = "\n".join(render_trace_section(
                events, k=args.exemplars))
    if args.out:
        with open(args.out, "w") as f:
            f.write(doc + "\n")
    else:
        print(doc)
    return 0


if __name__ == "__main__":
    sys.exit(main())
