#!/usr/bin/env python
"""Capture the replicated-log convergence record (the log-subsystem
PR's acceptance artifact).

Runs the sharded replicated-log driver on the 4-device pull fabric
under ONE mixed nemesis fault program — a crash/recover event, a
permanent crash, an open partition window, and a drop-rate ramp — and
gates:

  * ``log_conv == 1.0``: EVERY eventually-alive node's full log row
    (entry planes + committed-offset vector) equals the acked-appends
    ground truth (integer-exact full-row equality, divided once on
    the host);
  * the partition STALL is visible: while the committed window is
    open, nobody holds the global truth (log_conv == 0 for those
    rounds);
  * 1-device/4-device trajectory parity BITWISE (the fabric's
    mesh-invariance contract, re-proven on the committed evidence);
  * the truth summary (per-key acked lengths + committed counts)
    agrees between the mesh and single-device drivers.

Everything lands in one run ledger (utils/telemetry — provenance first
line; the drivers flush their ``round_metrics`` events with the
``log_conv`` column), so the committed artifact passes
tools/validate_artifacts.py's ``*kafka*`` provenance gate.

    python tools/kafka_capture.py [OUT.jsonl]    # default
        artifacts/ledger_kafka_r15.jsonl

Runs on the hermetic CPU tier by design (log convergence is integer
arithmetic, not a chip rate).
"""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N = 64
DEVICES = 4
MAX_ROUNDS = 24
PARTITION_END = 6


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    out_path = (argv[0] if argv else
                os.path.join(REPO, "artifacts",
                             "ledger_kafka_r15.jsonl"))
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={DEVICES}"
        ).strip()

    import numpy as np
    from gossip_tpu.config import (ChurnConfig, FaultConfig, LogConfig,
                                   ProtocolConfig, RunConfig)
    from gossip_tpu.models.log import simulate_curve_log
    from gossip_tpu.parallel.sharded import make_mesh
    from gossip_tpu.parallel.sharded_log import (
        simulate_curve_log_sharded)
    from gossip_tpu.topology import generators as G
    from gossip_tpu.utils import telemetry

    proto = ProtocolConfig(mode="pull", fanout=2)
    topo = G.complete(N)
    run = RunConfig(seed=0, max_rounds=MAX_ROUNDS, target_coverage=1.0)
    mesh = make_mesh(DEVICES)
    # the mixed fault program: crash/recover, permanent crash, open
    # partition window, drop ramp — every schedule feature at once
    fault = FaultConfig(drop_prob=0.05, seed=1, churn=ChurnConfig(
        events=((3, 2, 5), (7, 1, -1)),
        partitions=((0, PARTITION_END, N // 2),),
        ramp=(1, 4, 0.0, 0.3)))
    cfg = LogConfig(keys=4, capacity=8)

    led = telemetry.Ledger(out_path)
    prev = telemetry.activate(led)
    ok = True
    try:
        led.record_runtime()
        led.event("kafka_fault_program",
                  events=[list(e) for e in fault.churn.events],
                  partitions=[list(w) for w in fault.churn.partitions],
                  ramp=list(fault.churn.ramp), drop_prob=fault.drop_prob,
                  n=N, keys=cfg.keys, capacity=cfg.capacity,
                  max_rounds=MAX_ROUNDS)
        with led.span("kafka:log", keys=cfg.keys):
            conv4, msgs4, fin4, truth4 = simulate_curve_log_sharded(
                cfg, proto, topo, run, mesh, fault)
            conv1, msgs1, fin1, truth1 = simulate_curve_log(
                cfg, proto, topo, run, fault)
        parity = bool(
            (np.asarray(conv1) == np.asarray(conv4)).all()
            and (np.asarray(fin1.val)
                 == np.asarray(fin4.val)[:N]).all()
            and truth1 == truth4)
        stalled = bool(all(c < 1.0 for c in conv4[:PARTITION_END]))
        ok = bool(conv4[-1] == 1.0) and parity and stalled
        led.event("kafka_scenario",
                  log_conv_final=float(conv4[-1]),
                  log_conv_curve=[round(float(c), 6) for c in conv4],
                  truth=truth4,
                  msgs=float(msgs4[-1]),
                  partition_stall_rounds=PARTITION_END,
                  partition_stalled=stalled,
                  mesh_parity_bitwise=parity,
                  devices=DEVICES, ok=ok)
        led.event("kafka_verdict", ok=ok)
    finally:
        telemetry.activate(prev)
        led.close()
    print(json.dumps({"out": out_path, "ok": ok}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
