"""Shared device-timing scaffold for the capture tools.

One definition (roofline.py and kernel_numbers.py both time chained
round applications): ``timed_chain`` returns SECONDS per iteration —
callers convert to ms at the call site, so there is exactly one unit
in this file and no ms/s twin to drift."""

import time


def timed_chain(step, init, iters: int) -> float:
    """Median-of-3 wall seconds per iteration for ``iters`` chained
    applications of ``step`` (i, carry) -> carry inside ONE jitted
    fori_loop — no host dispatch in the measured region."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def chain(t0):
        return jax.lax.fori_loop(
            0, iters, lambda i, t: step(jnp.int32(i), t), t0)

    out = chain(init)                   # compile + warm
    jax.block_until_ready(out)
    samples = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = chain(init)
        jax.block_until_ready(out)
        samples.append((time.perf_counter() - t0) / iters)
    return sorted(samples)[1]
