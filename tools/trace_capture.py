#!/usr/bin/env python
"""Trace capture: the committed proof that request tracing survives a
replica SIGKILL end to end.

tools/fleet_crashloop.py proved the fleet loses no acked request under
kills; this tool proves every one of those requests is ATTRIBUTABLE
afterwards (docs/OBSERVABILITY.md "Request tracing & live metrics").
It runs a 3-replica fleet behind a router, drives the load-harness mix
with one client-minted trace id per request, SIGKILLs K replicas at a
seeded mid-load acked threshold, and gates:

  * **joinable complete waterfalls** — every acked request's trace id
    joins across the shared multi-writer ledger (router half + replica
    half, tools/trace_report.py) INCLUDING the failover-replayed ones
    (a re-dispatched request leaves two replica halves; the last is
    the acked attempt and the join must still close);
  * **fleet-status sees the kill and the recovery** — the same
    degradation predicate the CLI exits nonzero on
    (gossip_tpu.cli._fleet_degraded over the router's Metrics reply)
    reports degraded after the SIGKILL and healthy again after the
    probe hysteresis re-admits the respawn;
  * **zero steady-state cost** — a post-recovery steady window of
    traced requests completes with ZERO backend compiles and ZERO new
    fsyncs on every replica AND on the router's own ledger, verified
    from ``compiles_total`` / ``ledger_fsyncs`` in the Metrics replies
    at the window edges (never by trust: rpc/sidecar._metrics reads
    the live counters) — tracing rides the flight recorder's
    write-through (sync=False) path and costs the timed path nothing.

Replica children share ONE ledger file via GOSSIP_TELEMETRY in their
env (the multi-writer append contract: every emit is one flushed
write framed by newlines, so concurrent writers at worst cost blank
lines every reader skips).  The committed record is
``artifacts/ledger_trace_r22.jsonl`` (provenance first line;
tools/validate_artifacts.py refuses any ``*trace*`` artifact without
provenance, never grandfathered).

    python tools/trace_capture.py            # committed-record config:
        # 3 replicas, 32 requests, K=1 seeded mid-load SIGKILL ->
        # artifacts/ledger_trace_r22.jsonl
    python tools/trace_capture.py --smoke --out /tmp/trace.jsonl

Runs on the hermetic CPU tier by design (replica children pinned to
JAX_PLATFORMS=cpu, shared compile cache): the tracing contract is a
join/zero-cost structure, not a chip rate.
"""

import argparse
import json
import os
import random
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import trace_report  # noqa: E402
from fleet_crashloop import kill_thresholds  # noqa: E402
from load_harness import distinct_requests, request_mix  # noqa: E402

DEFAULT_OUT = os.path.join(REPO, "artifacts", "ledger_trace_r22.jsonl")


def _fleet_rows(m: dict) -> dict:
    """Per-replica (compiles_total, ledger_fsyncs) from one router
    Metrics reply — the steady-window edge snapshot.  A row without a
    metrics leaf (dead / unreachable replica) is reported as None so
    the caller fails the zero-cost gate loudly instead of skipping."""
    out = {}
    for row in m.get("fleet", ()):
        rm = row.get("metrics")
        out[row["replica"]] = (
            None if rm is None
            else (rm.get("compiles_total"), rm.get("ledger_fsyncs")))
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--kills", type=int, default=1,
                    help="seeded mid-load replica SIGKILLs (the "
                         "committed record carries K=1 on 3 replicas)")
    ap.add_argument("--kill-seed", type=int, default=22,
                    help="seeds the kill threshold and victim draw "
                         "(a failing sequence replays exactly)")
    ap.add_argument("--n", type=int, default=256)
    ap.add_argument("--rounds", type=int, default=16)
    ap.add_argument("--repeats", type=int, default=8,
                    help="repeats of the 4-shape load-harness mix")
    ap.add_argument("--workers", type=int, default=8)
    ap.add_argument("--steady", type=int, default=6,
                    help="post-recovery steady-window requests (the "
                         "zero-compile / zero-fsync gate)")
    ap.add_argument("--timeout-s", type=float, default=300.0,
                    help="per-request client deadline (bounds queue "
                         "wait + run + failover end to end)")
    ap.add_argument("--probe-interval-ms", type=float, default=200.0)
    ap.add_argument("--up-after", type=int, default=3)
    ap.add_argument("--replica-platform", default="cpu",
                    help="JAX_PLATFORMS pin for replica children "
                         "('' inherits the ambient platform)")
    ap.add_argument("--workdir", default=None,
                    help="replica log/cache scratch dir (default: a "
                         "fresh temp dir)")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny live fleet: 2 replicas, 8 requests "
                         "(every gate still enforced)")
    ap.add_argument("--out", default=None,
                    help="ledger path (default: the committed record "
                         "path, '.smoke'-infixed under --smoke — the "
                         "hw_refresh rehearsal convention)")
    a = ap.parse_args(argv)
    if a.out is None:
        a.out = (DEFAULT_OUT.replace(".jsonl", ".smoke.jsonl")
                 if a.smoke else DEFAULT_OUT)
    if a.smoke:
        a.replicas = min(a.replicas, 2)
        a.repeats = min(a.repeats, 2)
        a.workers = min(a.workers, 4)
        a.n = min(a.n, 128)
        a.rounds = min(a.rounds, 8)
        a.steady = min(a.steady, 3)
    a.kills = min(a.kills, max(1, a.replicas - 1))

    if a.workdir is None:
        import tempfile
        a.workdir = tempfile.mkdtemp(prefix="trace_capture_")
    os.makedirs(a.workdir, exist_ok=True)

    from gossip_tpu.cli import _fleet_degraded
    from gossip_tpu.config import FleetConfig
    from gossip_tpu.rpc.router import Fleet, fleet_env
    from gossip_tpu.rpc.sidecar import SidecarClient
    from gossip_tpu.utils import telemetry

    # a fresh record every run: the artifact is THIS capture's story,
    # not an accumulation of every rehearsal that ever targeted it
    if os.path.exists(a.out):
        os.remove(a.out)
    led = telemetry.Ledger(a.out)   # router + tool events land here
    prev = telemetry.activate(led)
    fleet = None
    client = None
    try:
        led.record_runtime()
        requests = request_mix(n=a.n, rounds=a.rounds,
                               repeats=a.repeats)
        total = len(requests)
        thresholds, rng = kill_thresholds(a.kills, total, a.kill_seed)
        led.event("config", replicas=a.replicas, kills=a.kills,
                  kill_seed=a.kill_seed, kill_thresholds=thresholds,
                  requests=total, workers=a.workers, n=a.n,
                  rounds=a.rounds, steady=a.steady,
                  smoke=bool(a.smoke))

        # ---- the fleet: children append to OUR ledger file ----------
        cfg = FleetConfig(replicas=a.replicas,
                          probe_interval_ms=a.probe_interval_ms,
                          up_after=a.up_after,
                          max_inflight=max(8, a.workers))
        env = fleet_env(
            compile_cache_dir=os.path.join(a.workdir, "cache"),
            platform=a.replica_platform or None)
        env["GOSSIP_TELEMETRY"] = led.path
        fleet = Fleet(cfg=cfg, workdir=a.workdir, env=env,
                      max_workers=a.workers + 4)
        if not fleet.router.wait_healthy(a.replicas, timeout_s=60):
            raise RuntimeError("fleet never reached full health at "
                               "startup")
        # warm each replica DIRECTLY (the router would steer all
        # serial warmup at one replica); the shared cache dir serves
        # replicas 1..N-1 and every respawn from replica 0's compiles
        t0 = time.perf_counter()
        distinct = distinct_requests(requests)
        for r in fleet.router.replicas:
            c = SidecarClient(r.address, max_attempts=1)
            for req in distinct:
                c.run(timeout=a.timeout_s, **req)
            c.close()
        led.event("warmup_done",
                  wall_s=round(time.perf_counter() - t0, 3),
                  distinct=len(distinct))

        # ---- measured run: traced concurrent load + seeded kill -----
        tids = [telemetry.new_trace_id() for _ in range(total)]
        replies = [None] * total
        errors = []
        acked = {"count": 0}
        cursor = {"i": 0}
        lock = threading.Lock()

        def worker():
            c = SidecarClient(fleet.address, max_attempts=1)
            while True:
                with lock:
                    i = cursor["i"]
                    if i >= total:
                        break
                    cursor["i"] = i + 1
                try:
                    replies[i] = c.run(timeout=a.timeout_s,
                                       trace_id=tids[i], **requests[i])
                    with lock:
                        acked["count"] += 1
                except Exception as e:
                    with lock:
                        errors.append(
                            f"req {i}: {type(e).__name__}: "
                            f"{str(e).splitlines()[0][:200]}")
            c.close()

        client = SidecarClient(fleet.address, max_attempts=1)

        def poll_status(want_degraded, timeout_s, tag):
            """Poll the router's Metrics reply with the CLI's OWN
            degradation predicate until it reports the wanted state;
            ledger a fleet_status event either way (the record of
            fleet-status seeing the kill / the recovery)."""
            deadline = time.monotonic() + timeout_s
            reasons, m = [], None
            while time.monotonic() < deadline:
                try:
                    m = client.metrics(timeout=10.0)
                    reasons = _fleet_degraded(m)
                except Exception as e:    # noqa: BLE001 — mid-kill
                    # transport blips are the thing being observed
                    reasons = [f"metrics poll failed: "
                               f"{type(e).__name__}"]
                    m = None
                if bool(reasons) == want_degraded:
                    break
                time.sleep(0.05)
            led.event("fleet_status", tag=tag,
                      degraded=bool(reasons), reasons=reasons[:8],
                      healthy=(m or {}).get("healthy"),
                      replicas=(m or {}).get("replicas"),
                      failovers=((m or {}).get("counters") or {})
                      .get("failovers"))
            return bool(reasons) == want_degraded

        led.event("load_phase", phase="measure_start")
        t0 = time.perf_counter()
        threads = [threading.Thread(target=worker)
                   for _ in range(a.workers)]
        for t in threads:
            t.start()
        kills_done = 0
        kill_acked = []
        saw_degraded = False
        for threshold in thresholds:
            while True:
                with lock:
                    now_acked = acked["count"]
                    done = cursor["i"] >= total
                if now_acked >= threshold:
                    break
                if done and not any(t.is_alive() for t in threads):
                    break
                time.sleep(0.002)
            with lock:
                now_acked = acked["count"]
            if now_acked >= total:
                led.event("kill_vacuous", threshold=threshold,
                          acked=now_acked)
                break
            live = [i for i, r in enumerate(fleet.router.replicas)
                    if r.proc is not None and r.proc.poll() is None
                    and r.healthy]
            if not live:
                led.event("kill_skipped", threshold=threshold,
                          reason="no healthy replica to interrupt")
                continue
            victim = rng.choice(live)
            pid = fleet.kill(victim)
            kills_done += 1
            kill_acked.append(now_acked)
            led.event("kill", seq=kills_done, replica=victim, pid=pid,
                      threshold=threshold, acked=now_acked,
                      run_id=led.run_id)
            # fleet-status must SEE the kill before the respawn is
            # re-admitted: the probe marks the victim down within
            # down_after * probe_interval, load keeps flowing on the
            # survivors while we watch
            saw_degraded |= poll_status(True, timeout_s=30.0,
                                        tag=f"after_kill_{kills_done}")
            addr = fleet.restart(victim)
            led.event("respawn", replica=victim, address=addr)
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        led.event("load_phase", phase="measure_end",
                  wall_s=round(wall, 3),
                  rps=round(total / wall, 2) if wall else None)

        # ---- recovery: fleet-status must report healthy again -------
        recovered = fleet.router.wait_healthy(a.replicas,
                                              timeout_s=120)
        saw_recovered = poll_status(False, timeout_s=60.0,
                                    tag="after_recovery")
        stats = fleet.router.stats()
        led.event("recovered", ok=recovered, **stats)

        # ---- steady window: tracing must cost NOTHING ---------------
        # re-warm the respawn directly so any (cache-served) compile
        # lands OUTSIDE the measured window, then snapshot the live
        # counters at both edges via the Metrics plane itself
        for r in fleet.router.replicas:
            c = SidecarClient(r.address, max_attempts=1)
            for req in distinct:
                c.run(timeout=a.timeout_s, **req)
            c.close()
        steady_tids = [telemetry.new_trace_id()
                       for _ in range(a.steady)]
        m0 = client.metrics(timeout=10.0)
        edge0 = _fleet_rows(m0)
        router_fsyncs0 = led.fsyncs
        for j, tid in enumerate(steady_tids):
            client.run(timeout=a.timeout_s, trace_id=tid,
                       **distinct[j % len(distinct)])
        m1 = client.metrics(timeout=10.0)
        edge1 = _fleet_rows(m1)
        router_fsyncs_delta = led.fsyncs - router_fsyncs0
        steady_cost = {"router_fsyncs_delta": router_fsyncs_delta,
                       "replicas": {}}
        cost_problems = []
        for idx in sorted(edge1):
            b, e = edge0.get(idx), edge1.get(idx)
            if b is None or e is None:
                cost_problems.append(
                    f"replica {idx} had no metrics leaf at a steady "
                    "window edge — zero-cost unverifiable")
                continue
            compiles = (None if b[0] is None or e[0] is None
                        else e[0] - b[0])
            fsyncs = (None if b[1] is None or e[1] is None
                      else e[1] - b[1])
            steady_cost["replicas"][idx] = {
                "compiles_delta": compiles, "fsyncs_delta": fsyncs}
            if compiles not in (0, None):
                cost_problems.append(
                    f"replica {idx} compiled {compiles}x inside the "
                    "steady window — tracing is not free")
            if fsyncs != 0:
                cost_problems.append(
                    f"replica {idx} fsynced {fsyncs}x inside the "
                    "steady window — a sync emit leaked into the "
                    "request path")
        if router_fsyncs_delta != 0:
            cost_problems.append(
                f"router ledger fsynced {router_fsyncs_delta}x inside "
                "the steady window")
        led.event("steady_cost", ok=not cost_problems, **steady_cost)

        # ---- the join: every acked request attributable -------------
        events = telemetry.load_ledger(a.out)   # ALL writers' runs
        joined = trace_report.join_traces(events)
        missing, incomplete = [], []
        for tid in tids + steady_tids:
            rec = joined.get(tid)
            if rec is None:
                missing.append(tid)
                continue
            if not trace_report.waterfall(rec)["complete"]:
                incomplete.append(tid)
        replayed = [tid for tid in tids
                    if tid in joined
                    and joined[tid]["attempts"] > 1]
        replayed_complete = [
            tid for tid in replayed
            if trace_report.waterfall(joined[tid])["complete"]]

        # ---- verdict ------------------------------------------------
        problems = list(errors) + cost_problems
        if kills_done < a.kills:
            problems.append(f"only {kills_done}/{a.kills} kills "
                            "landed (raise --repeats)")
        for k, at in enumerate(kill_acked):
            if not 0 < at < total:
                problems.append(f"kill {k + 1} landed at acked={at} "
                                f"of {total} — not mid-load")
        if not recovered:
            problems.append(
                f"fleet never recovered to {a.replicas} healthy "
                f"replicas (healthy={stats['healthy']})")
        if kills_done and not saw_degraded:
            problems.append("fleet-status never reported the kill "
                            "(no degraded poll after SIGKILL)")
        if not saw_recovered:
            problems.append("fleet-status never reported recovery "
                            "(degraded at the final poll)")
        router_events = [e for e in events
                         if e.get("run") == led.run_id]

        def count(kind):
            return sum(1 for e in router_events
                       if e.get("ev") == kind)
        if count("replica_down") < kills_done:
            problems.append("fewer replica_down events than kills")
        if kills_done and count("failover") < 1:
            problems.append("no failover event: no in-flight request "
                            "was ever re-dispatched")
        if missing:
            problems.append(f"{len(missing)} acked trace ids never "
                            f"joined (e.g. {missing[:3]})")
        if incomplete:
            problems.append(f"{len(incomplete)} joined traces lack a "
                            "router or replica half "
                            f"(e.g. {incomplete[:3]})")
        if kills_done and count("failover") and not replayed:
            problems.append("failovers happened but no joined trace "
                            "shows >1 dispatch attempt")
        if replayed and not replayed_complete:
            problems.append("no failover-replayed trace joined to a "
                            "complete waterfall")
        led.event("verdict", ok=not problems, kills=kills_done,
                  kill_acked=kill_acked, requests=total,
                  acked=acked["count"], errors=len(errors),
                  traces=len(tids) + len(steady_tids),
                  joined=len(tids) + len(steady_tids) - len(missing),
                  complete=len(tids) + len(steady_tids)
                  - len(missing) - len(incomplete),
                  replayed=len(replayed),
                  replayed_complete=len(replayed_complete),
                  failovers=stats["failovers"],
                  recovered_full_capacity=recovered,
                  fleet_status_saw_kill=saw_degraded,
                  fleet_status_saw_recovery=saw_recovered,
                  healthy=stats["healthy"],
                  steady_cost=steady_cost, problems=problems)
        if problems:
            for p in problems:
                print(f"TRACE CAPTURE FAIL: {p}", file=sys.stderr)
            return 1
        print(json.dumps({
            "ok": True, "kills": kills_done, "requests": total,
            "acked": acked["count"],
            "traces": len(tids) + len(steady_tids),
            "complete_waterfalls": len(tids) + len(steady_tids),
            "replayed": len(replayed),
            "failovers": stats["failovers"],
            "healthy": stats["healthy"],
            "steady_compiles_delta": 0,
            "steady_fsyncs_delta": 0,
            "ledger": a.out}))
        return 0
    finally:
        if client is not None:
            client.close()
        if fleet is not None:
            fleet.close()
        telemetry.activate(prev)
        led.close()


if __name__ == "__main__":
    sys.exit(main())
