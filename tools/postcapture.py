#!/usr/bin/env python
"""Render every r05 hardware artifact into doc-ready markdown.

After the watchdog lands a hardware refresh (artifacts/*_r05.json),
the numbers must flow into README.md's hardware table and docs/PERF.md
— during what may be a short window of human attention.  This tool
collapses that to one read: it prints, for every r05 artifact that
exists, a markdown-ready block plus the decisions the numbers imply
(e.g. the swim_diss default flip if pack won).  Read-only; prints
"missing" for artifacts not yet captured, so it also serves as a
capture-progress report.

    python tools/postcapture.py
"""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SMOKE = "--smoke" in sys.argv[1:]     # rehearse on the .smoke artifacts


def _art_name(name):
    if SMOKE:
        stem, dot, ext = name.rpartition(".")
        name = f"{stem}.smoke.{ext}" if dot else name
    return name


def load(name):
    try:
        with open(os.path.join(REPO, "artifacts", _art_name(name))) as f:
            return json.load(f)
    except OSError:
        return None


def section(title):
    print(f"\n## {title}\n")


def main():
    any_found = False

    doc = load("hw_refresh_r05.json")
    section("Capture status (hw_refresh_r05.json)")
    if doc is None:
        print("missing — no refresh attempt has landed yet")
    else:
        any_found = True
        for r in doc:
            mark = "ok" if r.get("ok") else (
                "TIMEOUT" if r.get("timed_out") else "FAILED")
            print(f"- {r['step']}: {mark} ({r.get('wall_s')} s)"
                  + ("" if r.get("ok") else
                     f" — {r.get('error', '')[:120]}"))

    ab = load("swim_diss_ab_r05.json")
    section("SWIM dissemination A/B (swim_diss_ab_r05.json)")
    if ab is None:
        print("missing")
    else:
        any_found = True
        for r in ab.get("rows", []):
            print(f"- {r['swim_diss']}: wall {r['wall_s']:.1f} s = "
                  f"compile {r['compile_s']:.1f} + steady "
                  f"{r['steady_wall_s']:.1f} s "
                  f"({r['rounds']} rounds, cov {r['coverage']:.4f})")
        print(f"- trajectories identical: "
              f"{ab.get('trajectories_identical')}")
        print(f"- verdict: {ab.get('verdict')}")
        if ab.get("winner") == "pack":
            print("- ACTION: flip ProtocolConfig.swim_diss default to "
                  "'pack' (config.py + CLI default + docstrings; "
                  "trajectories bitwise-identical so tests stay green)")
        elif ab.get("winner"):
            print(f"- ACTION: none — '{ab['winner']}' confirmed as "
                  "default")

    sweep = None
    path = os.path.join(REPO, "artifacts",
                        _art_name("baseline_sweep_r05.jsonl"))
    if os.path.exists(path):
        with open(path) as f:
            sweep = [json.loads(x) for x in f if x.strip()]
    section("Five-config sweep (baseline_sweep_r05.jsonl)")
    if not sweep:
        print("missing")
    else:
        any_found = True
        print("README 'BASELINE configs measured on hardware' table "
              "(tools/readme_table.py rendering):\n")
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        try:
            import readme_table
            readme_table.main(path)
        finally:
            sys.path.pop(0)
        for r in sweep:
            m = r.get("meta") or {}
            if m.get("swim_diss_effective"):
                print(f"\nSWIM row ran swim_diss="
                      f"{m['swim_diss_effective']}, swim_rng="
                      f"{m.get('swim_rng')}")

    kn = load("kernel_numbers_r05.json")
    section("Kernel provenance re-measurement (kernel_numbers_r05.json)")
    if kn is None:
        print("missing")
    else:
        any_found = True
        sr = kn["single_rumor"]
        print(f"- fused single-rumor at N={sr['n']}: "
              f"{sr['ms_per_round']} ms/round "
              f"({sr['node_rounds_per_s']:.3g} node-rounds/s)")
        f2 = kn.get("mr_staged_fanout2")
        if f2:
            print(f"- staged big-MR fanout 2 at N={f2['n']}x"
                  f"{f2['rumors']}: {f2['ms_per_round']} ms/round")
        oom = kn["vmem_oom_ladder"]
        if oom.get("value_kernel_compiles"):
            print("- VMEM ladder: value kernel unexpectedly compiled "
                  "(re-check _VMEM_LIMIT_BYTES vs chip)")
        else:
            print(f"- VMEM ladder: value kernel at {oom['table_mib']} "
                  f"MiB table OOMs as designed; XLA message captured")
        tb = kn["topology_build"]
        print(f"- {tb['n']}-node power-law build: {tb['build_s']} s")
        fm = kn["fault_mask"]
        print(f"- fault masks at N={fm['n']}: off "
              f"{fm['masks_off_ms_per_round']} ms -> on "
              f"{fm['masks_on_ms_per_round']} ms/round "
              f"({fm['on_cost_pct']:+.1f}%)")

    rf = load("roofline_r05.json")
    section("Roofline (roofline_r05.json)")
    if rf is None:
        print("missing")
    else:
        any_found = True
        s = rf["single_rumor"]
        print(f"- single-rumor: {s['actual_ms_per_round']} ms/round vs "
              f"floors serial {s['floor_serial_ms']} / overlap "
              f"{s['floor_overlap_ms']} ms -> utilization "
              f"{s['utilization_vs_serial']:.0%} (serial) / "
              f"{s['utilization_vs_overlap']:.0%} (overlap)")
        fc = s["floor_components_ms"]
        print(f"  components: prng {fc['prng']} ms, gather "
              f"{fc['gather']} ms, vpu {fc['vpu']} ms")
        if not s.get("gather_floor_resolved", True):
            print("  WARNING: gather rate unresolved (differential "
                  "below noise) — the floors are lower bounds missing "
                  "the gather term; re-run before quoting utilization")
        dom = max(fc, key=fc.get)
        print(f"  dominant primitive: {dom} — the harvest target if "
              "utilization is high and actual >> floor")
        s2 = s.get("actual_ms_plane_sharing2")
        if s2 is not None:
            verdict = ("WINS — consider shipping as the bench variant"
                       if s2 < s["actual_ms_per_round"] * 0.95
                       else "no win")
            print(f"  plane_sharing=2 (half the PRNG words): {s2} "
                  f"ms/round -> {verdict}")
        m = rf["mr_staged"]
        print(f"- staged MR: {m['actual_ms_per_round']} ms/round vs HBM "
              f"floor {m['floor_ms_fused_rotation']} ms (fused rot) / "
              f"{m['floor_ms_materialized_rotation']} ms (materialized)"
              f" -> {m['utilization_vs_fused_floor']:.0%} of the fused-"
              f"rotation floor; rotation fuses: {m['rotation_fuses']}")

    ab2 = load("swim_steady_ablation_r05.json")
    section("SWIM steady decomposition (swim_steady_ablation_r05.json)")
    if ab2 is None:
        print("missing")
    else:
        any_found = True
        for r in ab2.get("rows", []):
            print(f"- {r['variant']}: {r['ms_per_round']} ms/round "
                  f"(delta vs full {r.get('delta_vs_full_ms', '?')})")

    ens = load("ensembles_r05.json")
    section("Hardware ensembles (ensembles_r05.json)")
    if ens is None:
        print("missing")
    else:
        any_found = True
        for name, sub in ens.items():
            if not isinstance(sub, dict):
                continue
            if not sub.get("ok"):
                print(f"- {name}: FAILED — {sub.get('error', '')[:120]}")
                continue
            e = (sub.get("report") or {}).get("ensemble") or {}
            print(f"- {name}: seeds {e.get('seeds')}, converged "
                  f"{e.get('converged')}, rounds p50 {e.get('rounds_p50')}"
                  f" p95 {e.get('rounds_p95')}")

    if not any_found:
        print("\n(no r05 hardware artifacts yet — the watchdog is "
              "presumably still probing; artifacts/ledger_tunnel_"
              "watchdog.jsonl has the probe history, rendered by "
              "tools/telemetry_report.py)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
