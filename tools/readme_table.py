#!/usr/bin/env python
"""Render the README hardware table from a baseline-sweep JSONL artifact.

Usage:
    python tools/readme_table.py artifacts/baseline_sweep_r02b.jsonl

Prints the markdown table with the round-3 contract columns — wall,
compile, and steady-state separated (RunReport meta ``compile_s`` /
``steady_wall_s``; multi-device sharded engines report one fused wall,
shown as '—').  Paste over the table in README.md's "BASELINE configs
measured on hardware" section after a hardware refresh
(tools/hw_refresh.py step 'baseline_sweep' writes the artifact).
"""

import json
import sys


def fmt_s(v):
    if v is None:
        return "—"
    return f"{v:.1f} s" if v >= 0.095 else f"{v * 1e3:.0f} ms"


def main(path):
    rows = []
    with open(path) as f:
        for line in f:
            if line.strip():
                rows.append(json.loads(line))
    print("| config | n | rounds to target | coverage / detection "
          "| wall | compile | steady |")
    print("|---|---|---|---|---|---|---|")
    for r in rows:
        meta = r.get("meta", {})
        n = r["n"]
        n_str = (f"{n // 1_000_000}M" if n >= 1_000_000 and
                 n % 1_000_000 == 0 else
                 f"{n // 1000}k" if n >= 1000 and n % 1000 == 0 else
                 str(n))
        print(f"| {r['config']} | {n_str} | {r['rounds']} "
              f"| {round(r['coverage'], 4)} | {fmt_s(r['wall_s'])} "
              f"| {fmt_s(meta.get('compile_s'))} "
              f"| {fmt_s(meta.get('steady_wall_s'))} |")
    return 0


if __name__ == "__main__":
    if len(sys.argv) != 2:
        print(__doc__, file=sys.stderr)
        sys.exit(2)
    sys.exit(main(sys.argv[1]))
