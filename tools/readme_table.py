#!/usr/bin/env python
"""Render the README hardware table from a baseline-sweep JSONL artifact.

Usage:
    python tools/readme_table.py artifacts/baseline_sweep_r02b.jsonl
    python tools/readme_table.py --dryrun-budgets MULTICHIP_r05.json \\
        [MULTICHIP_r06.json]

Prints the markdown table with the round-3 contract columns — wall,
compile, and steady-state separated (RunReport meta ``compile_s`` /
``steady_wall_s``; multi-device sharded engines report one fused wall,
shown as '—').  Paste over the table in README.md's "BASELINE configs
measured on hardware" section after a hardware refresh
(tools/hw_refresh.py step 'baseline_sweep' writes the artifact).

``--dryrun-budgets`` renders the per-family steady-state budget table
instead (docs/PERF.md "Dry-run steady-state budget"): families and
budgets from tools/dryrun_budgets.json, measured steady_ms columns from
one or two dry-run records — either a MULTICHIP_rNN.json (the table is
parsed out of its ``tail``) or a raw ``{"dryrun_family_ms": ...}``
dump.  With two records the first renders as "before" and the second
as "after".
"""

import json
import os
import sys


def fmt_s(v):
    if v is None:
        return "—"
    return f"{v:.1f} s" if v >= 0.095 else f"{v * 1e3:.0f} ms"


def main(path):
    rows = []
    with open(path) as f:
        for line in f:
            if line.strip():
                rows.append(json.loads(line))
    print("| config | n | rounds to target | coverage / detection "
          "| wall | compile | steady |")
    print("|---|---|---|---|---|---|---|")
    for r in rows:
        meta = r.get("meta", {})
        n = r["n"]
        n_str = (f"{n // 1_000_000}M" if n >= 1_000_000 and
                 n % 1_000_000 == 0 else
                 f"{n // 1000}k" if n >= 1000 and n % 1000 == 0 else
                 str(n))
        print(f"| {r['config']} | {n_str} | {r['rounds']} "
              f"| {round(r['coverage'], 4)} | {fmt_s(r['wall_s'])} "
              f"| {fmt_s(meta.get('compile_s'))} "
              f"| {fmt_s(meta.get('steady_wall_s'))} |")
    return 0


def _load_family_ms(path):
    """The ``dryrun_family_ms`` table out of a dry-run record: a raw
    dump, or a MULTICHIP_rNN.json whose ``tail`` holds the JSON line —
    scanned by telemetry.parse_dryrun_table, the one parser of the
    dry-run stdout contract (jax-free import)."""
    with open(path) as f:
        rec = json.load(f)
    if "dryrun_family_ms" in rec:
        return rec["dryrun_family_ms"]
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    try:
        from gossip_tpu.utils.telemetry import parse_dryrun_table
    finally:
        sys.path.pop(0)
    parsed = parse_dryrun_table(rec.get("tail", ""))
    if parsed is not None:
        return parsed["dryrun_family_ms"]
    raise ValueError(f"{path} carries no dryrun_family_ms table")


def main_dryrun_budgets(paths):
    if not 1 <= len(paths) <= 2:
        print("--dryrun-budgets takes one record (steady_ms) or two "
              "(before/after)", file=sys.stderr)
        return 2
    budgets_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "dryrun_budgets.json")
    with open(budgets_path) as f:
        budgets = json.load(f)
    tables = [_load_family_ms(p) for p in paths]
    cols = (["steady_ms (before)", "steady_ms (after)"] if len(tables) == 2
            else ["steady_ms"])
    print("| family | " + " | ".join(cols) + " | budget_ms |")
    print("|---|" + "---|" * (len(cols) + 1))
    for fam in budgets:
        cells = [str(t[fam]["steady_ms"]) if fam in t else "—"
                 for t in tables]
        print(f"| {fam} | " + " | ".join(cells) + f" | {budgets[fam]} |")
    return 0


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--dryrun-budgets":
        sys.exit(main_dryrun_budgets(sys.argv[2:]))
    if len(sys.argv) != 2:
        print(__doc__, file=sys.stderr)
        sys.exit(2)
    sys.exit(main(sys.argv[1]))
