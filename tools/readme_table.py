#!/usr/bin/env python
"""Render the README hardware table from a baseline-sweep JSONL artifact.

Usage:
    python tools/readme_table.py artifacts/baseline_sweep_r02b.jsonl
    python tools/readme_table.py --dryrun-budgets MULTICHIP_r05.json \\
        [MULTICHIP_r06.json]
    python tools/readme_table.py --first-budgets \\
        artifacts/ledger_dryrun_r08.jsonl

Prints the markdown table with the round-3 contract columns — wall,
compile, and steady-state separated (RunReport meta ``compile_s`` /
``steady_wall_s``; multi-device sharded engines report one fused wall,
shown as '—').  Paste over the table in README.md's "BASELINE configs
measured on hardware" section after a hardware refresh
(tools/hw_refresh.py step 'baseline_sweep' writes the artifact).

``--dryrun-budgets`` renders the per-family steady-state budget table
instead (docs/PERF.md "Dry-run steady-state budget"): families and
budgets from tools/dryrun_budgets.json, measured steady_ms columns from
one or two dry-run records — either a MULTICHIP_rNN.json (the table is
parsed out of its ``tail``) or a raw ``{"dryrun_family_ms": ...}``
dump.  With two records the first renders as "before" and the second
as "after".
"""

import json
import os
import sys


def fmt_s(v):
    if v is None:
        return "—"
    return f"{v:.1f} s" if v >= 0.095 else f"{v * 1e3:.0f} ms"


def main(path):
    rows = []
    with open(path) as f:
        for line in f:
            if line.strip():
                rows.append(json.loads(line))
    print("| config | n | rounds to target | coverage / detection "
          "| wall | compile | steady |")
    print("|---|---|---|---|---|---|---|")
    for r in rows:
        meta = r.get("meta", {})
        n = r["n"]
        n_str = (f"{n // 1_000_000}M" if n >= 1_000_000 and
                 n % 1_000_000 == 0 else
                 f"{n // 1000}k" if n >= 1000 and n % 1000 == 0 else
                 str(n))
        print(f"| {r['config']} | {n_str} | {r['rounds']} "
              f"| {round(r['coverage'], 4)} | {fmt_s(r['wall_s'])} "
              f"| {fmt_s(meta.get('compile_s'))} "
              f"| {fmt_s(meta.get('steady_wall_s'))} |")
    return 0


def _load_family_ms(path):
    """The ``dryrun_family_ms`` table out of a dry-run record: a raw
    dump, or a MULTICHIP_rNN.json whose ``tail`` holds the JSON line —
    scanned by telemetry.parse_dryrun_table, the one parser of the
    dry-run stdout contract (jax-free import)."""
    with open(path) as f:
        rec = json.load(f)
    if "dryrun_family_ms" in rec:
        return rec["dryrun_family_ms"]
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    try:
        from gossip_tpu.utils.telemetry import parse_dryrun_table
    finally:
        sys.path.pop(0)
    parsed = parse_dryrun_table(rec.get("tail", ""))
    if parsed is not None:
        return parsed["dryrun_family_ms"]
    raise ValueError(f"{path} carries no dryrun_family_ms table")


def _load_budget_table(table):
    """One table out of tools/dryrun_budgets.json, via the sibling
    report tool's loader — ONE parser of the two-table format
    (telemetry_report.load_budgets), not a second drifting copy."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    try:
        from telemetry_report import load_budgets
    finally:
        sys.path.pop(0)
    budgets = load_budgets(table=table)
    if not budgets:
        raise ValueError(
            f"tools/dryrun_budgets.json has no usable {table!r} table")
    return budgets


def main_dryrun_budgets(paths):
    if not 1 <= len(paths) <= 2:
        print("--dryrun-budgets takes one record (steady_ms) or two "
              "(before/after)", file=sys.stderr)
        return 2
    budgets = _load_budget_table("steady_ms")
    tables = [_load_family_ms(p) for p in paths]
    cols = (["steady_ms (before)", "steady_ms (after)"] if len(tables) == 2
            else ["steady_ms"])
    print("| family | " + " | ".join(cols) + " | budget_ms |")
    print("|---|" + "---|" * (len(cols) + 1))
    for fam in budgets:
        cells = [str(t[fam]["steady_ms"]) if fam in t else "—"
                 for t in tables]
        print(f"| {fam} | " + " | ".join(cells) + f" | {budgets[fam]} |")
    return 0


def _ledger_family_runs(path):
    """[(run_id, {family: row})] for every run in a dry-run ledger that
    carries ``family`` events, file order — run 1 of the committed
    warm-start artifact is the cold process, run 2 the warm one."""
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    try:
        from gossip_tpu.utils.telemetry import load_ledger
    finally:
        sys.path.pop(0)
    events = load_ledger(path)
    by_run = {}
    order = []
    for e in events:
        if e.get("ev") == "family" and e.get("run") is not None:
            if e["run"] not in by_run:
                order.append(e["run"])
            by_run.setdefault(e["run"], {})[e["family"]] = {
                k: v for k, v in e.items()
                if k not in ("ev", "ts", "run", "family")}
    return [(r, by_run[r]) for r in order]


def main_first_budgets(paths):
    """The compile-once cold/warm first-round table (docs/PERF.md):
    per-family first_ms from the cold and warm runs of a dry-run
    LEDGER (two runs in one file — the r08 artifact shape — or two
    single-run ledgers), against the ``first_warm_ms`` budgets the
    warm process is held to."""
    if not 1 <= len(paths) <= 2:
        print("--first-budgets takes one dry-run ledger (cold+warm "
              "runs in file order) or two (cold, warm)", file=sys.stderr)
        return 2
    runs = [fr for p in paths for fr in _ledger_family_runs(p)]
    if len(runs) < 2:
        print(f"need a cold and a warm run; found {len(runs)} run(s) "
              "with family events", file=sys.stderr)
        return 2
    budgets = _load_budget_table("first_warm_ms")
    cold, warm = runs[0][1], runs[-1][1]
    print("| family | first_ms (cold) | first_ms (warm) | speedup "
          "| first_warm_budget_ms |")
    print("|---|---|---|---|---|")
    tc = tw = 0.0
    # union, budget order first: a ledger family the budget table has
    # not caught up with still renders (with '—' for its budget), and
    # the totals only count families present in BOTH runs — a one-
    # sided row must not inflate the headline speedup
    fams = list(budgets) + sorted((set(cold) | set(warm)) - set(budgets))
    for fam in fams:
        c = cold.get(fam, {}).get("first_ms")
        w = warm.get(fam, {}).get("first_ms")
        if c is not None and w is not None:
            tc += c
            tw += w
        speed = f"{c / w:.1f}x" if c and w else "—"
        b = budgets.get(fam, "—")
        print(f"| {fam} | {c if c is not None else '—'} "
              f"| {w if w is not None else '—'} | {speed} "
              f"| {b} |")
    if tw:
        print(f"| **total** | **{round(tc, 1)}** | **{round(tw, 1)}** "
              f"| **{tc / tw:.1f}x** | — |")
    return 0


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--dryrun-budgets":
        sys.exit(main_dryrun_budgets(sys.argv[2:]))
    if len(sys.argv) >= 3 and sys.argv[1] == "--first-budgets":
        sys.exit(main_first_budgets(sys.argv[2:]))
    if len(sys.argv) != 2:
        print(__doc__, file=sys.stderr)
        sys.exit(2)
    sys.exit(main(sys.argv[1]))
