#!/usr/bin/env python
"""Capture the byzantine-adversary convergence record (the Byzantine
nemesis PR's acceptance artifact).

One mixed scenario, two arms, one provenance-stamped ledger:

* **Scenario** — a 16-node complete-graph pull fabric under a MIXED
  nemesis program: one fail-stop churn event (node 4 dies at round 6,
  recovers at 12) plus a scripted liar program (node 3 INFLATES
  foreign components from round 2, node 11 CORRUPTS them with a
  high-bit xor from round 0; quorum 2).  Liar content never enters
  the compiled loop — the byz program lowers to padded integer
  operands on the step's table tail (ops/nemesis), so both arms below
  share ONE executable per driver.

* **Defended arm** (``defend=True``) — the array-form lattice
  defenses (owner-column admission, monotonicity clamps, provenance-
  checked register entries).  Gate: the honest eventual-alive set
  converges EXACTLY — ``byz_conv == denominator/denominator`` as an
  integer count, the value_conv discipline — for both the gcounter
  and the LWW-register payloads.

* **Undefended arm** (``defend=False``, the control) — the same
  executable shape with the defenses off MUST diverge: the liars'
  forged components stick under max/OR/LWW merge and the honest count
  stays below the denominator.  A defense whose absence changes
  nothing defends nothing.

* **Mesh parity** — the defended trajectory is BITWISE identical on a
  1-device and a 4-device mesh, and equal to the single-device model
  driver (the fabric's mesh-invariance contract, re-proven on the
  committed evidence).  The sharded runs flush their
  ``round_metrics`` events with the ``byz_conv`` column into the same
  ledger.

Everything lands in one run ledger (utils/telemetry — provenance
first line), so the committed artifact passes
tools/validate_artifacts.py's ``*byz*`` provenance gate.

    python tools/byzantine_capture.py [--smoke] [OUT.jsonl]
        # default artifacts/ledger_byz_r25.jsonl
        # --smoke: gcounter leg only, .smoke-infixed artifact
        #          (the hw_refresh convention)

Runs on the hermetic CPU tier by design (byz convergence is integer
arithmetic on the honest-owned components, not a chip rate).
"""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N = 16
DEVICES = 4
MAX_ROUNDS = 100
FANOUT = 3
LIARS = ((3, 2, "inflate", 5), (11, 0, "corrupt", 1 << 20))
QUORUM = 2
CHURN_EVENTS = ((4, 6, 12),)


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    smoke = "--smoke" in argv
    argv = [a for a in argv if a != "--smoke"]
    infix = ".smoke" if smoke else ""
    out_path = (argv[0] if argv else
                os.path.join(REPO, "artifacts",
                             f"ledger_byz_r25{infix}.jsonl"))
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={DEVICES}"
        ).strip()

    import jax
    import numpy as np
    from jax.sharding import Mesh
    from gossip_tpu.config import (ByzConfig, ChurnConfig, CrdtConfig,
                                   FaultConfig, ProtocolConfig,
                                   RunConfig, TxnConfig)
    from gossip_tpu.models.crdt import simulate_curve_crdt
    from gossip_tpu.models.register import simulate_curve_txn
    from gossip_tpu.ops import crdt as CR
    from gossip_tpu.ops import nemesis as NE
    from gossip_tpu.ops import registers as RG
    from gossip_tpu.parallel.sharded_crdt import (
        simulate_curve_crdt_sharded)
    from gossip_tpu.parallel.sharded_register import (
        simulate_curve_txn_sharded)
    from gossip_tpu.topology.generators import complete
    from gossip_tpu.utils import telemetry

    topo = complete(N)
    proto = ProtocolConfig(mode="pull", fanout=FANOUT)
    run = RunConfig(max_rounds=MAX_ROUNDS, seed=7)
    byz = ByzConfig(liars=LIARS, quorum=QUORUM)
    fault = FaultConfig(churn=ChurnConfig(events=CHURN_EVENTS),
                        byz=byz)
    mesh1 = Mesh(np.array(jax.devices()[:1]), ("nodes",))
    mesh4 = Mesh(np.array(jax.devices()[:DEVICES]), ("nodes",))

    led = telemetry.Ledger(out_path)
    prev = telemetry.activate(led)
    ok = True
    try:
        led.record_runtime()
        led.event("byz_fault_program",
                  liars=[list(a) for a in LIARS], quorum=QUORUM,
                  churn_events=[list(e) for e in CHURN_EVENTS],
                  n=N, fanout=FANOUT, max_rounds=MAX_ROUNDS,
                  smoke=smoke)

        # -- gcounter leg: defended exact vs undefended divergence ----
        cfg = CrdtConfig(kind="gcounter")
        with led.span("byz:gcounter"):
            conv_u, _, fin_u, _ = simulate_curve_crdt(
                cfg, proto, topo, run, fault, defend=False)
            conv_d, _, fin_d, _ = simulate_curve_crdt(
                cfg, proto, topo, run, fault, defend=True)
        inj = CR.inject_args(cfg, N)
        truth = CR.ground_truth(cfg, inj, fault, N, 0)
        honest = NE.honest_mask(fault, N)
        alive_h = CR.eventual_alive_crdt(fault, N, 0) & honest
        comp = CR.honest_component_mask(cfg, N, 0, honest)
        denom = int(alive_h.sum())
        cnt_u = int(CR.byz_converged_count(cfg, fin_u.val, truth,
                                           alive_h, comp))
        cnt_d = int(CR.byz_converged_count(cfg, fin_d.val, truth,
                                           alive_h, comp))

        # mesh parity: defended trajectory bitwise across mesh widths
        # (the sharded runs flush round_metrics w/ byz_conv into the
        # ledger under the active telemetry)
        with led.span("byz:mesh_parity"):
            _, _, f1, _ = simulate_curve_crdt_sharded(
                cfg, proto, topo, run, mesh1, fault, defend=True)
            c4, _, f4, _ = simulate_curve_crdt_sharded(
                cfg, proto, topo, run, mesh4, fault, defend=True)
        parity = bool(
            np.array_equal(np.asarray(f1.val)[:N],
                           np.asarray(f4.val)[:N])
            and np.array_equal(np.asarray(f1.val)[:N],
                               np.asarray(fin_d.val))
            and np.array_equal(np.asarray(conv_d), np.asarray(c4)))
        counter_ok = bool(cnt_d == denom and cnt_u < denom
                          and denom > 0 and parity)
        led.event("byz_scenario", payload="gcounter",
                  defended_count=cnt_d, undefended_count=cnt_u,
                  denominator=denom,
                  defended_exact=bool(cnt_d == denom),
                  undefended_diverged=bool(cnt_u < denom),
                  mesh_parity_bitwise=parity, devices=DEVICES,
                  defended_curve=[round(float(c), 6) for c in conv_d],
                  undefended_curve=[round(float(c), 6)
                                    for c in conv_u],
                  ok=counter_ok)
        ok = ok and counter_ok

        # -- register leg (skipped in smoke: one payload class is
        # enough to smoke the plumbing; the full capture proves the
        # provenance defense on the LWW timestamps too) --------------
        if not smoke:
            cfgt = TxnConfig(txns=12, keys=6, spread_rounds=8)
            with led.span("byz:register"):
                ru = simulate_curve_txn(cfgt, proto, topo, run, fault,
                                        defend=False)
                rd = simulate_curve_txn(cfgt, proto, topo, run, fault,
                                        defend=True)
                r4 = simulate_curve_txn_sharded(cfgt, proto, topo,
                                                run, mesh4, fault,
                                                defend=True)
            injt = RG.inject_args(cfgt, N)
            trt = RG.ground_truth(cfgt, injt, fault, N, 0)
            alive_ht = RG.eventual_alive_crdt(fault, N, 0) & honest
            km = RG.honest_key_mask(cfgt, injt, fault, N, 0, honest)
            denomt = int(alive_ht.sum())
            tcnt_u = int(RG.byz_converged_count(cfgt, ru[2].val, trt,
                                                alive_ht, km))
            tcnt_d = int(RG.byz_converged_count(cfgt, rd[2].val, trt,
                                                alive_ht, km))
            tparity = bool(np.array_equal(np.asarray(r4[2].val)[:N],
                                          np.asarray(rd[2].val)))
            txn_ok = bool(tcnt_d == denomt and tcnt_u < denomt
                          and denomt > 0 and tparity)
            led.event("byz_txn_scenario", keys=cfgt.keys,
                      defended_count=tcnt_d, undefended_count=tcnt_u,
                      denominator=denomt,
                      defended_exact=bool(tcnt_d == denomt),
                      undefended_diverged=bool(tcnt_u < denomt),
                      mesh_parity_bitwise=tparity, devices=DEVICES,
                      ok=txn_ok)
            ok = ok and txn_ok

        led.event("byz_verdict", ok=ok, smoke=smoke)
    finally:
        telemetry.activate(prev)
        led.close()
    print(json.dumps({"out": out_path, "ok": ok}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
