#!/usr/bin/env python
"""Join ``xla_compile`` attribution events into the per-engine cost
table — the "Executable costs" read-out of the XLA cost & memory
attribution plane (utils/compile_cache.load_or_compile, the ONE
acquisition chokepoint, docs/OBSERVABILITY.md).

Every chokepoint compile lands one ``xla_compile`` event carrying the
caller's driver label, the executable fingerprint, the cache verdict,
and XLA's own cost/memory analysis (flops, bytes accessed, argument/
output/temp bytes) — or explicit nulls where the backend reports none
(record-never-gate: a null renders ``n/a``, never a fabricated zero).
``cost_case`` events (tools/cost_capture.py) supply the plan shape
(nodes × rounds) so attributed bytes normalize to bytes/node/round —
the "where do the bytes go" number docs/PERF.md reasons with.
``budget_xcheck`` events (planner/budget.crosscheck_peak) render as
the measured≤predicted drift-gate table.

    python tools/cost_report.py ARTIFACT.jsonl          # last run
    python tools/cost_report.py ARTIFACT.jsonl --run RUNID

tools/telemetry_report.py embeds :func:`render_cost_section` so the
full-ledger report and this tool can never disagree about what an
``xla_compile`` event means (the one-reader-per-schema convention).
"""

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: xla_compile table columns pulled straight off the event (the
#: utils/compile_cache.ATTRIBUTION_FIELDS order, minus the arg/out/
#: temp decomposition the summary table folds into peak)
_COST_COLS = ("flops", "bytes_accessed", "peak_bytes")


def _telemetry():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    try:
        from _telemetry import telemetry
    finally:
        sys.path.pop(0)
    return telemetry()


def _fmt(v, unit=""):
    """``n/a`` for null attribution (a backend that reports none),
    thousands-grouped otherwise — a null must be visibly a null, never
    a zero someone averages."""
    if v is None:
        return "n/a"
    if isinstance(v, float) and not v.is_integer():
        return f"{v:,.1f}{unit}"
    return f"{int(v):,}{unit}"


def join_costs(events):
    """``{"rows": [...], "xchecks": [...], "cases": {...}}`` from one
    run's events.  ``rows`` has one entry per (label, fn) executable —
    an engine that compiles an init step and a round step keeps both
    visible — with the cache verdict, compile wall, attribution
    fields, and ``bytes_per_node_round`` when a ``cost_case`` event
    supplies that label's plan shape."""
    cases = {}
    for e in events:
        if e.get("ev") == "cost_case" and e.get("label"):
            cases[e["label"]] = {"n": e.get("n"),
                                 "rounds": e.get("rounds")}
    rows = []
    index = {}
    for e in events:
        if e.get("ev") != "xla_compile":
            continue
        label = e.get("label") or e.get("fn") or "?"
        key = (label, e.get("fn"))
        row = index.get(key)
        if row is None:
            row = {"label": label, "fn": e.get("fn"), "compiles": 0,
                   "verdicts": {}, "compile_ms": 0.0, "key": None,
                   **{c: None for c in _COST_COLS},
                   "bytes_per_node_round": None}
            index[key] = row
            rows.append(row)
        row["compiles"] += 1
        verdict = e.get("cache")
        row["verdicts"][verdict] = row["verdicts"].get(verdict, 0) + 1
        if e.get("compile_ms") is not None:
            row["compile_ms"] += e["compile_ms"]
        if e.get("key") is not None:
            row["key"] = e["key"]
        for c in _COST_COLS:
            if e.get(c) is not None:
                row[c] = e[c]
        case = cases.get(label)
        if (case and row["bytes_accessed"] is not None
                and case.get("n") and case.get("rounds")):
            row["bytes_per_node_round"] = (
                row["bytes_accessed"] / (case["n"] * case["rounds"]))
    xchecks = [{k: v for k, v in e.items()
                if k not in ("ev", "ts", "run")}
               for e in events if e.get("ev") == "budget_xcheck"]
    return {"rows": rows, "xchecks": xchecks, "cases": cases}


def render_cost_section(events):
    """Markdown lines for the "Executable costs" section, [] when the
    run carries no attribution events (pre-attribution ledgers render
    without the section, not with an empty table)."""
    joined = join_costs(events)
    if not joined["rows"] and not joined["xchecks"]:
        return []
    out = ["## Executable costs", ""]
    if joined["rows"]:
        out.append("| engine | fn | cache | compile_ms | flops "
                   "| bytes accessed | peak bytes | bytes/node/round |")
        out.append("|---|---|---|---|---|---|---|---|")
        for r in joined["rows"]:
            cache = ", ".join(f"{k}×{v}" if v > 1 else str(k)
                              for k, v in sorted(
                                  r["verdicts"].items(),
                                  key=lambda kv: str(kv[0])))
            bpnr = r["bytes_per_node_round"]
            out.append(
                f"| {r['label']} | {r['fn'] or '-'} | {cache} "
                f"| {r['compile_ms']:.1f} | {_fmt(r['flops'])} "
                f"| {_fmt(r['bytes_accessed'])} "
                f"| {_fmt(r['peak_bytes'])} "
                f"| {_fmt(round(bpnr, 1) if bpnr is not None else None)} |")
        out.append("")
    if joined["xchecks"]:
        out.append("### Budget cross-checks (measured ≤ predicted)")
        out.append("")
        out.append("| engine | n | tiles | predicted bytes "
                   "| measured bytes | verdict | headroom |")
        out.append("|---|---|---|---|---|---|---|")
        for x in joined["xchecks"]:
            ok = x.get("ok")
            verdict = ("n/a" if ok is None
                       else "green" if ok else "**EXCEEDED**")
            frac = x.get("headroom_frac")
            out.append(
                f"| {x.get('engine')} | {_fmt(x.get('n'))} "
                f"| {_fmt(x.get('tiles'))} "
                f"| {_fmt(x.get('predicted_bytes'))} "
                f"| {_fmt(x.get('measured_bytes'))} | {verdict} "
                f"| {f'{frac:.1%}' if frac is not None else 'n/a'} |")
        out.append("")
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("ledger", help="path to a telemetry JSONL ledger")
    ap.add_argument("--run", default="last",
                    help="run id to render (default: newest)")
    args = ap.parse_args(argv)
    events = _telemetry().load_ledger(args.ledger, run=args.run)
    lines = render_cost_section(events)
    if not lines:
        print(f"no xla_compile/budget_xcheck events in {args.ledger}",
              file=sys.stderr)
        return 1
    print("\n".join(lines))
    return 0


if __name__ == "__main__":
    sys.exit(main())
