#!/usr/bin/env python
"""Render a run ledger (utils/telemetry JSONL) into doc-ready markdown.

The one place artifacts get their numbers from (round 7): the dry-run
per-family table, the budget deltas against tools/dryrun_budgets.json,
the probe timeline of a capture window, and device-memory high-water
all come straight out of the ledger — no re-parsing of stdout, no
bespoke per-tool JSON.

    python tools/telemetry_report.py ARTIFACT.jsonl            # last run
    python tools/telemetry_report.py ARTIFACT.jsonl --run RUNID
    python tools/telemetry_report.py ARTIFACT.jsonl --all-runs
    python tools/telemetry_report.py ... -o report.md

A ledger written by a run that was SIGKILLed mid-flight still renders:
unclosed spans are reported as such (the flight-recorder read-out the
dark rounds needed), and a torn final line is dropped by the loader's
documented crash contract.
"""

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BUDGETS_PATH = os.path.join(REPO, "tools", "dryrun_budgets.json")


def _telemetry():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    try:
        from _telemetry import telemetry
    finally:
        sys.path.pop(0)
    return telemetry()


def load_ledger(path, run=None):
    return _telemetry().load_ledger(path, run=run)


def runs(events):
    """Run ids in file order (provenance lines define runs; lines from
    an unknown run — a truncated provenance — still count)."""
    seen = []
    for e in events:
        r = e.get("run")
        if r is not None and r not in seen:
            seen.append(r)
    return seen


def span_tree(events):
    """[(depth, node)] in start order; ``node`` has name/wall_ms/ok and
    ``unclosed=True`` when the run died before span_end (SIGKILL, outer
    timeout) — the span_start is durable by the fsync contract, so the
    tree still shows WHERE it died."""
    nodes = {}
    order = []
    for e in events:
        if e.get("ev") == "span_start":
            nodes[e["span"]] = {"span": e["span"], "parent": e.get("parent"),
                                "name": e.get("name"), "ts": e.get("ts"),
                                "unclosed": True,
                                "attrs": {k: v for k, v in e.items()
                                          if k not in ("ev", "ts", "run",
                                                       "span", "parent",
                                                       "name")}}
            order.append(e["span"])
        elif e.get("ev") == "span_end" and e.get("span") in nodes:
            n = nodes[e["span"]]
            n["unclosed"] = False
            n["wall_ms"] = e.get("wall_ms")
            n["ok"] = e.get("ok", True)
            n["attrs"].update({k: v for k, v in e.items()
                               if k not in ("ev", "ts", "run", "span",
                                            "parent", "name", "wall_ms",
                                            "ok")})

    def depth(sid):
        d = 0
        p = nodes[sid]["parent"]
        while p is not None and p in nodes:
            d += 1
            p = nodes[p]["parent"]
        return d

    return [(depth(s), nodes[s]) for s in order]


def family_table(events):
    """{family: row} from the dry run's ``family`` events — the exact
    per-family ms table the body printed on stdout, recovered from
    ledger data alone (first/steady plus the wall decomposition on the
    fused rows)."""
    table = {}
    for e in events:
        if e.get("ev") == "family":
            row = {k: v for k, v in e.items()
                   if k not in ("ev", "ts", "run", "family")}
            table[e["family"]] = row
    return table


def memory_high_water(events):
    """Max bytes_in_use / peak_bytes_in_use over every memory snapshot
    (span_end ``memory`` fields and standalone ``memory`` events), or
    None when the backend reported no stats (CPU)."""
    peak = {}
    for e in events:
        rows = []
        if e.get("ev") == "memory":
            rows = e.get("devices") or []
        elif e.get("ev") == "span_end" and e.get("memory"):
            rows = e["memory"]
        for r in rows:
            for k in ("bytes_in_use", "peak_bytes_in_use"):
                if isinstance(r.get(k), (int, float)):
                    peak[k] = max(peak.get(k, 0), r[k])
    return peak or None


def probe_timeline(events):
    """The capture-window read-out: every probe/fallback/measurement
    event with a time offset from the run's first event — 78 timed-out
    probes render as 78 rows with walls, not a lost stderr stream."""
    t0 = events[0]["ts"] if events else 0.0
    rows = []
    for e in events:
        if e.get("ev") in ("probe", "fallback", "measurement",
                           "measurement_failed", "body_abnormal_exit",
                           "refresh_start", "refresh_abort", "step",
                           "budget_guard"):
            rows.append({"t_offset_s": round(e["ts"] - t0, 1),
                         "ev": e["ev"],
                         **{k: v for k, v in e.items()
                            if k not in ("ev", "ts", "run")}})
    return rows


def load_budgets(path=BUDGETS_PATH, table="steady_ms"):
    """One per-family budget table (default the steady one).  The
    budgets file became two-table in the compile-once PR
    (``{"steady_ms": ..., "first_warm_ms": ...}``); a flat legacy file
    is read as the steady table so old records keep rendering."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return {}
    if isinstance(doc.get("steady_ms"), dict):
        return doc.get(table) or {}
    return doc if table == "steady_ms" else {}


def compile_cache_table(events):
    """The compile-once read-out: ``{"status", "rows", "totals"}`` from
    a run's cache events.  ``status`` is the last ``compile_cache``
    enable event (dir/persistent/knobs); ``rows`` is one entry per
    compile — the dry-run body's per-family ``compile`` events and the
    chokepoint's ``compile`` span_ends (utils/compile_cache) — each
    carrying ``cache: hit|miss|disabled``; ``totals`` counts rows by
    verdict.  Empty rows/None status on pre-compile-cache ledgers."""
    status = None
    rows = []
    totals = {}
    for e in events:
        row = None
        if e.get("ev") == "compile_cache":
            status = {k: v for k, v in e.items()
                      if k not in ("ev", "ts", "run")}
        elif e.get("ev") == "compile":
            row = {"where": e.get("family") or e.get("fn"),
                   "phase": e.get("phase"), "cache": e.get("cache"),
                   "ms": e.get("measured_ms"),
                   "hits": e.get("hits"), "misses": e.get("misses")}
        elif e.get("ev") == "span_end" and e.get("name") == "compile":
            row = {"where": e.get("fn"), "phase": "aot",
                   "cache": e.get("cache"), "ms": e.get("wall_ms")}
        if row is not None:
            rows.append(row)
            totals[row["cache"]] = totals.get(row["cache"], 0) + 1
    return {"status": status, "rows": rows, "totals": totals}


def _fmt(v):
    if isinstance(v, float):
        return f"{v:.1f}"
    return str(v)


def _protocol_metrics_section(events):
    """The "Protocol metrics" lines, rendered by the diff tool's ONE
    implementation (tools/ledger_diff.render_protocol_metrics) so the
    report and the cross-run gate can never disagree about what a
    ``round_metrics`` event means."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    try:
        from ledger_diff import render_protocol_metrics
    finally:
        sys.path.pop(0)
    return render_protocol_metrics(events)


def _serving_section(events):
    """The "Serving batches" lines, rendered by the batching tool's ONE
    implementation (tools/batching_report.render_serving_section — the
    rpc/batcher ``batch`` event schema has exactly one reader).  Empty
    for runs with no serving telemetry."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    try:
        from batching_report import render_serving_section
    finally:
        sys.path.pop(0)
    return render_serving_section(events)


def _trace_section(events):
    """The "Request traces" lines, rendered by the trace tool's ONE
    implementation (tools/trace_report.render_trace_section — the
    ``request_trace`` waterfall join has exactly one reader).  Empty
    for runs with no trace-bearing events."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    try:
        from trace_report import render_trace_section
    finally:
        sys.path.pop(0)
    return render_trace_section(events)


def _cost_section(events):
    """The "Executable costs" lines, rendered by the cost tool's ONE
    implementation (tools/cost_report.render_cost_section — the
    ``xla_compile``/``budget_xcheck`` attribution schema has exactly
    one reader).  Empty for runs with no attribution events."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    try:
        from cost_report import render_cost_section
    finally:
        sys.path.pop(0)
    return render_cost_section(events)


def check_health(events):
    """Ledger-health problems for the ``--check`` CI gate: a run whose
    evidence cannot be trusted mechanically.  Flags (a) a missing
    provenance line — numbers with no commit/toolchain attribution —
    and (b) unclosed spans: the writer died or wedged inside them
    (exactly what the flight recorder exists to show, and exactly what
    a green CI artifact must not contain)."""
    problems = []
    if not any(e.get("ev") == "provenance" for e in events):
        problems.append("no provenance line (run_id/git_commit/"
                        "captured) — pre-ledger file or torn before "
                        "first fsync")
    unclosed = [n["name"] for _, n in span_tree(events) if n["unclosed"]]
    for name in unclosed:
        problems.append(f"unclosed span {name!r} — the run was killed "
                        "or wedged inside it")
    return problems


def render_markdown(events, budgets=None, title=None,
                    trace_events=None):
    """``trace_events`` overrides the event set the "Request traces"
    section joins over: the waterfall halves are written by DIFFERENT
    processes (router run + replica runs in one multi-writer file), so
    a run-filtered view would render every trace incomplete — main()
    passes the whole file.  None = join the same events as the rest of
    the report (single-writer ledgers)."""
    budgets = load_budgets() if budgets is None else budgets
    out = []
    prov = next((e for e in events if e.get("ev") == "provenance"), None)
    rt = next((e for e in events if e.get("ev") == "runtime"), None)
    out.append(f"# {title or 'Run ledger report'}")
    out.append("")
    if prov:
        out.append(f"- run `{prov.get('run_id')}` captured "
                   f"{prov.get('captured')} at commit "
                   f"`{(prov.get('git_commit') or 'unknown')[:12]}` "
                   f"(jax {prov.get('jax_version')}, "
                   f"python {prov.get('python')})")
        out.append(f"- argv: `{' '.join(prov.get('argv', []))}`")
    else:
        out.append("- **no provenance line** (pre-ledger file or torn "
                   "before first fsync)")
    if rt:
        out.append(f"- backend `{rt.get('backend')}`, "
                   f"{rt.get('device_count')} device(s) "
                   f"({rt.get('device_kind')})")
    out.append("")

    fams = family_table(events)
    if fams:
        out.append("## Per-family dry-run walls (ms)")
        out.append("")
        decomp = any("steady_exec_ms" in r for r in fams.values())
        hdr = ["family", "first_ms", "steady_ms", "budget_ms",
               "headroom_ms"]
        if decomp:
            hdr += ["steady_exec_ms", "init_build_ms",
                    "driver_overhead_ms"]
        out.append("| " + " | ".join(hdr) + " |")
        out.append("|" + "---|" * len(hdr))
        for fam, row in fams.items():
            budget = budgets.get(fam)
            cells = [fam, _fmt(row.get("first_ms", "")),
                     _fmt(row.get("steady_ms", "")),
                     _fmt(budget) if budget is not None else "-",
                     _fmt(budget - row["steady_ms"])
                     if budget is not None and "steady_ms" in row else "-"]
            if decomp:
                cells += [_fmt(row[k]) if k in row else "-"
                          for k in ("steady_exec_ms", "init_build_ms",
                                    "driver_overhead_ms")]
            out.append("| " + " | ".join(cells) + " |")
        out.append("")
        guard = [e for e in events if e.get("ev") == "budget_guard"]
        if guard:
            g = guard[-1]
            verdict = ("**green**" if g.get("ok") else
                       f"**TRIPPED**: {g.get('over') or g.get('missing')}")
            out.append(f"Budget guard (tools/dryrun_budgets.json): "
                       f"{verdict}.")
            out.append("")

    cache = compile_cache_table(events)
    if cache["status"] or cache["rows"]:
        out.append("## Compile cache")
        out.append("")
        st = cache["status"]
        if st:
            out.append(f"- cache dir `{st.get('dir')}` "
                       f"(persistent={st.get('persistent')})")
        if cache["totals"]:
            out.append("- compiles by verdict: " + ", ".join(
                f"{k}={v}" for k, v in sorted(cache["totals"].items(),
                                              key=lambda kv: str(kv[0]))))
        if cache["rows"]:
            out.append("")
            out.append("| where | phase | cache | ms |")
            out.append("|---|---|---|---|")
            for r in cache["rows"]:
                out.append(f"| {r['where']} | {r.get('phase') or '-'} "
                           f"| {r['cache']} "
                           f"| {_fmt(r['ms']) if r.get('ms') is not None else '-'} |")
        out.append("")

    out.extend(_protocol_metrics_section(events))
    out.extend(_serving_section(events))
    out.extend(_trace_section(events if trace_events is None
                              else trace_events))
    out.extend(_cost_section(events))

    tree = span_tree(events)
    if tree:
        out.append("## Span tree")
        out.append("")
        for depth, n in tree:
            pad = "  " * depth
            if n["unclosed"]:
                out.append(f"{pad}- `{n['name']}` — **unclosed** (run "
                           "killed/wedged inside this span)")
            else:
                flag = "" if n.get("ok", True) else " **[raised]**"
                out.append(f"{pad}- `{n['name']}` — "
                           f"{n['wall_ms']:.1f} ms{flag}")
        out.append("")

    mem = memory_high_water(events)
    out.append("## Device memory high-water")
    out.append("")
    if mem:
        for k, v in sorted(mem.items()):
            out.append(f"- {k}: {v:,} bytes")
    else:
        out.append("- no device memory snapshots in this run (CPU "
                   "backends report none)")
    out.append("")

    probes = probe_timeline(events)
    if probes:
        out.append("## Event timeline")
        out.append("")
        out.append("| t+s | event | detail |")
        out.append("|---|---|---|")
        for r in probes:
            detail = ", ".join(f"{k}={_fmt(v) if isinstance(v, float) else v}"
                               for k, v in r.items()
                               if k not in ("t_offset_s", "ev")
                               and not isinstance(v, (dict, list)))
            out.append(f"| {r['t_offset_s']} | {r['ev']} | {detail} |")
        out.append("")

    counters = {}
    for e in events:
        if e.get("ev") == "counter":
            counters[e["name"]] = e.get("total")
    if counters:
        out.append("## Counters (final totals)")
        out.append("")
        for k, v in sorted(counters.items()):
            out.append(f"- {k}: {v}")
        out.append("")
    return "\n".join(out)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("ledger", help="path to a telemetry JSONL ledger")
    ap.add_argument("--run", default="last",
                    help="run id to render (default: the newest run in "
                         "the file)")
    ap.add_argument("--all-runs", action="store_true",
                    help="render every run in the file, newest last")
    ap.add_argument("--budgets", default=BUDGETS_PATH,
                    help="per-family steady budget JSON for the delta "
                         "column (default: tools/dryrun_budgets.json)")
    ap.add_argument("-o", "--out", default=None,
                    help="write markdown here instead of stdout")
    ap.add_argument("--check", action="store_true",
                    help="ledger-health gate: exit 1 (no render) on "
                         "unclosed spans or missing provenance — for "
                         "CI (checks every run with --all-runs, else "
                         "the selected one)")
    args = ap.parse_args(argv)

    all_events = load_ledger(args.ledger)

    def run_events(r):
        return [e for e in all_events if e.get("run") == r]

    def selected_run(rs):
        """args.run resolved against the one parse (the load_ledger
        run= semantics, without a second full read of the file) via
        the diff tool's ONE resolver, so an unknown explicit id is an
        ERROR here too — never an empty selection that --check would
        misdiagnose as a torn/pre-ledger file."""
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        try:
            from ledger_diff import resolve_run_id
        finally:
            sys.path.pop(0)
        return resolve_run_id(rs, args.run, args.ledger,
                              tool="telemetry_report")

    if args.check:
        problems = []
        rs = runs(all_events)
        if not rs:
            problems += check_health(all_events)
        elif args.all_runs:
            for r in rs:
                problems += [f"run {r}: {p}"
                             for p in check_health(run_events(r))]
        else:
            problems = check_health(run_events(selected_run(rs)))
        name = os.path.basename(args.ledger)
        if problems:
            for p in problems:
                print(f"FAIL {name}: {p}", file=sys.stderr)
            return 1
        print(f"{name}: ledger health OK")
        return 0
    budgets = load_budgets(args.budgets)
    name = os.path.basename(args.ledger)
    if args.all_runs:
        # per-run parts suppress the trace section (trace_events=[]):
        # the halves of one waterfall live in different writers' runs,
        # so the join is rendered ONCE over the whole file instead
        parts = [render_markdown(
            [e for e in all_events if e.get("run") == r], budgets,
            title=f"{name} — run {r}", trace_events=[])
            for r in runs(all_events)]
        traces = _trace_section(all_events)
        if traces:
            parts.append("\n".join(
                [f"# {name} — cross-run trace join", ""] + traces))
        doc = "\n\n".join(parts)
    else:
        rs = runs(all_events)
        events = run_events(selected_run(rs)) if rs else all_events
        if not events:
            print(f"no events for run {args.run!r} in {args.ledger}",
                  file=sys.stderr)
            return 1
        doc = render_markdown(events, budgets, title=name,
                              trace_events=all_events)
    if args.out:
        with open(args.out, "w") as f:
            f.write(doc + "\n")
    else:
        print(doc)
    return 0


if __name__ == "__main__":
    sys.exit(main())
