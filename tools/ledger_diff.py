#!/usr/bin/env python
"""Join two run ledgers and report wall / budget / round-metric deltas
— the mechanical cross-run regression gate.

Before this tool, cross-run regressions were caught only by the
hand-tuned absolute budget tables (tools/dryrun_budgets.json): a family
could triple its wall and still sit under a generous budget, and two
committed records could only be compared by eyeballing two markdown
renders.  This tool joins two ledgers the way the data says they join —
by FAMILY, PHASE, and COMPILE VERDICT — and flags what actually moved:

  * **walls** — per-family ``steady_ms`` and ``first_ms`` ratios,
    CALIBRATED by the run-pair's median drift: the per-kind
    leave-one-out median of the comparable families' new/old ratios
    (each family is judged against its PEERS' median, clamped to
    >= 1, so its own regression never calibrates itself away — even
    with one comparable family) is divided out before thresholding,
    so a loaded host that inflates EVERY wall ~2x uniformly — exactly
    what a dry run at the tail of a 12-minute CI session measures —
    never gates, while one family that moves 1.8x beyond the pack
    always does (a code regression is family-shaped; host load is
    uniform).  A wall is then flagged only
    when BOTH the calibrated ratio threshold and an absolute floor are
    exceeded (small CPU walls are noisy; a 3 ms -> 7 ms jitter must not
    gate a PR, a 2x jump on a half-second compile must).  ``first_ms``
    is compared ONLY between runs with the SAME compile verdict (hit
    vs hit, miss vs miss): a cold run "regressing" against a warm one
    is the cache working, not a regression — the verdict join is what
    makes the committed cold+warm records directly diffable against
    any fresh run.
  * **budgets** — the new run's steady walls against the current
    tools/dryrun_budgets.json (the absolute backstop, re-checked here
    so a diff against an old record can't bless an over-budget run).
  * **round metrics** — per-driver protocol totals (ops/round_metrics:
    newly/dup/msgs/bytes).  Trajectories are seeded and deterministic,
    so AT THE SAME DEVICE COUNT the totals must match almost exactly —
    a drifted ``msgs`` total is a protocol change, not noise.  Across
    different device counts the join is reported informationally and
    never flagged (sparse stratification and padding are
    mesh-dependent by design).

Exit code: 0 when nothing is flagged, 1 otherwise — wire it straight
into CI.  ``python tools/ledger_diff.py OLD.jsonl NEW.jsonl`` (each
defaults to its file's newest run; ``--run-old/--run-new`` take a run
id, ``first``, or ``last``).  Thresholds: ``--ratio`` (default 1.8),
``--steady-floor-ms`` (50), ``--first-floor-ms`` (250),
``--metrics-ratio`` (1.05).

Also home to the "Protocol metrics" renderer
(:func:`render_protocol_metrics`) that tools/telemetry_report.py embeds
— one implementation of the round-metric table for both tools.
"""

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _telemetry():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    try:
        from _telemetry import telemetry
    finally:
        sys.path.pop(0)
    return telemetry()


def _load_budgets():
    """tools/dryrun_budgets.json steady table via the report tool's one
    parser of the two-table format (never a second drifting copy)."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    try:
        from telemetry_report import load_budgets
    finally:
        sys.path.pop(0)
    return load_budgets()


def resolve_run_id(runs, which, path, tool="ledger_diff"):
    """``last``/``first``/explicit-id resolved against a run-id list.
    An unknown explicit id must ERROR, not silently select an empty
    run and exit clean — both CI gates (this tool and
    telemetry_report --check) share that contract by sharing this
    code."""
    if which == "last":
        return runs[-1]
    if which == "first":
        return runs[0]
    if which not in runs:
        raise SystemExit(
            f"{tool}: run {which!r} not in {path} "
            f"(runs: {', '.join(runs)})")
    return which


def select_run(path, which="last"):
    """Events of one run of a ledger file: ``last`` (default),
    ``first``, or an explicit run id."""
    t = _telemetry()
    events = t.load_ledger(path)
    runs = []
    for e in events:
        r = e.get("run")
        if r is not None and r not in runs:
            runs.append(r)
    if not runs:
        return events
    which = resolve_run_id(runs, which, path)
    return [e for e in events if e.get("run") == which]


def extract(events):
    """The diffable view of one run: provenance, device count, the
    per-family wall rows joined with their first-call compile verdict,
    and the last round-metrics totals per driver."""
    prov = next((e for e in events if e.get("ev") == "provenance"), {})
    rt = next((e for e in events if e.get("ev") == "runtime"), {})
    families = {}
    for e in events:
        if e.get("ev") == "family":
            families[e["family"]] = {
                k: v for k, v in e.items()
                if k not in ("ev", "ts", "run", "family")}
    for e in events:
        if e.get("ev") == "compile" and e.get("phase") == "first_ms" \
                and e.get("family") in families:
            families[e["family"]]["verdict"] = e.get("cache")
    metrics = {}
    for drv, e in _indexed_metric_events(events):
        metrics[drv] = {"rounds": e.get("rounds"),
                        "shards": e.get("shards"),
                        **(e.get("totals") or {})}
    # serving legs (tools/load_harness load_leg events) ride along
    # informationally: rps and the percentile columns are carried so
    # latency regressions are *diffable*, but they NEVER flag — walls
    # never gate (wall-clock under a thread harness is host-load
    # noise; the gates that matter — bitwise parity, steady-all-warm —
    # live in the capture's own gate events)
    serving = {}
    for e in events:
        if e.get("ev") == "load_leg" and e.get("leg"):
            serving[e["leg"]] = {
                k: e.get(k) for k in ("rps", "p50_ms", "p95_ms",
                                      "p99_ms", "devices", "replicas")
                if e.get(k) is not None}
    # the request-trace join summary (tools/trace_report via
    # load_harness's trace_join event) rides along informationally
    # too: per-request waterfall quantiles are thread-harness walls —
    # the trace event kinds (request_trace / dispatch_attempt /
    # trace_admit / trace_join) NEVER join the gated totals above
    tj = next((e for e in events if e.get("ev") == "trace_join"), None)
    traces = None
    if tj is not None:
        traces = {"traces": tj.get("traces"),
                  "complete": tj.get("complete"),
                  "replayed": tj.get("replayed"),
                  "expired": tj.get("expired"),
                  "wall_p50_ms": (tj.get("wall_ms") or {}).get("p50"),
                  "wall_p99_ms": (tj.get("wall_ms") or {}).get("p99")}
    return {"run_id": prov.get("run_id"),
            "captured": prov.get("captured"),
            "git_commit": prov.get("git_commit"),
            "device_count": rt.get("device_count"),
            "families": families, "metrics": metrics,
            "serving": serving, "traces": traces}


def _indexed_metric_events(events):
    """``[(key, event)]`` for a run's round_metrics events, where key
    is the driver label — suffixed ``#k`` by invocation order when a
    label repeats (the fused dry-run families SHARE driver labels:
    plain and fault-curve both flush ``simulate_*_sharded_fused``).
    Keeping only the last event per label would silently drop the
    earlier invocation's totals from both the diff and the report;
    invocation order is deterministic (seeded runs, one program
    order), so the suffix is a stable join key."""
    rms = [e for e in events if e.get("ev") == "round_metrics"]
    counts = {}
    for e in rms:
        d = e.get("driver")
        counts[d] = counts.get(d, 0) + 1
    seen, out = {}, []
    for e in rms:
        d = e.get("driver")
        k = seen.get(d, 0)
        seen[d] = k + 1
        out.append((d if counts[d] == 1 else f"{d}#{k}", e))
    return out


def _median(xs):
    xs = sorted(xs)
    mid = len(xs) // 2
    return (xs[mid] if len(xs) % 2
            else 0.5 * (xs[mid - 1] + xs[mid]))


def _wall_ratios(old, new, kind, verdict_matched=False):
    """{family: new/old wall ratio} over the comparable families."""
    ratios = {}
    for fam, o in old["families"].items():
        n = new["families"].get(fam)
        if n is None:
            continue
        if verdict_matched and o.get("verdict") != n.get("verdict"):
            continue
        a, b = o.get(kind), n.get(kind)
        if isinstance(a, (int, float)) and isinstance(b, (int, float)) \
                and a > 0:
            ratios[fam] = b / a
    return ratios


def _drift(ratios, exclude=None):
    """max(1, median) of the OTHER families' wall ratios — the uniform
    host-load factor divided out before thresholding a family (clamped
    at 1: a faster new environment must not mask an absolute
    regression).  Leave-one-out: a family's own ratio never calibrates
    itself, else a regression with few comparable peers — one family:
    ANY regression — would absorb its own signal and pass clean."""
    xs = [r for f, r in ratios.items() if f != exclude]
    return max(1.0, _median(xs)) if xs else 1.0


def diff(old, new, ratio=1.8, steady_floor_ms=50.0,
         first_floor_ms=250.0, metrics_ratio=1.05, budgets=None):
    """{"rows", "metric_rows", "flags", "notes", "drift"} — the joined
    deltas.  ``flags`` are regression verdicts (nonzero exit);
    ``notes`` are join caveats (verdict mismatches, device-count
    mismatches) that explain why something was NOT compared; ``drift``
    is the per-kind median calibration divided out of the wall ratios
    (module doc)."""
    budgets = _load_budgets() if budgets is None else budgets
    flags, notes, rows = [], [], []
    ratios = {"steady_ms": _wall_ratios(old, new, "steady_ms"),
              "first_ms": _wall_ratios(old, new, "first_ms",
                                       verdict_matched=True)}
    # the pair-wide medians, for the report header (thresholding uses
    # the per-family leave-one-out variant)
    drift = {k: _drift(r) for k, r in ratios.items()}

    def wall_flag(fam, kind, a, b, floor):
        if a is None or b is None:
            return None
        cal = _drift(ratios[kind], exclude=fam)
        if b >= ratio * cal * a and (b - cal * a) >= floor:
            flags.append(f"{fam} {kind} regressed {a:.1f} -> {b:.1f} ms "
                         f"({b / max(a, 1e-9):.2f}x raw, "
                         f"{b / max(cal * a, 1e-9):.2f}x beyond the "
                         f"peers' median drift {cal:.2f}x >= {ratio}x, "
                         f"delta >= {floor:.0f} ms)")
            return True
        return False

    fams = sorted(set(old["families"]) | set(new["families"]))
    for fam in fams:
        o = old["families"].get(fam)
        n = new["families"].get(fam)
        if o is None or n is None:
            notes.append(f"{fam}: only in "
                         f"{'new' if o is None else 'old'} run")
            continue
        row = {"family": fam,
               "steady_old": o.get("steady_ms"),
               "steady_new": n.get("steady_ms"),
               "first_old": o.get("first_ms"),
               "first_new": n.get("first_ms"),
               "verdict_old": o.get("verdict"),
               "verdict_new": n.get("verdict"),
               "budget_ms": budgets.get(fam)}
        row["steady_flag"] = wall_flag(fam, "steady_ms",
                                       o.get("steady_ms"),
                                       n.get("steady_ms"),
                                       steady_floor_ms)
        if o.get("verdict") == n.get("verdict"):
            row["first_flag"] = wall_flag(fam, "first_ms",
                                          o.get("first_ms"),
                                          n.get("first_ms"),
                                          first_floor_ms)
        else:
            row["first_flag"] = None
            notes.append(
                f"{fam}: compile verdict {o.get('verdict')} vs "
                f"{n.get('verdict')} — first_ms not compared (a warm "
                "run against a cold one measures the cache, not the "
                "code)")
        b = budgets.get(fam)
        if b is not None and n.get("steady_ms") is not None \
                and n["steady_ms"] > b:
            row["budget_flag"] = True
            flags.append(f"{fam} steady_ms {n['steady_ms']:.1f} over "
                         f"budget {b} (tools/dryrun_budgets.json)")
        rows.append(row)

    same_mesh = (old.get("device_count") is not None
                 and old.get("device_count") == new.get("device_count"))
    metric_rows = []
    for drv in sorted(set(old["metrics"]) | set(new["metrics"])):
        o = old["metrics"].get(drv)
        n = new["metrics"].get(drv)
        if o is None or n is None:
            notes.append(f"round_metrics[{drv}]: only in "
                         f"{'new' if o is None else 'old'} run")
            continue
        row = {"driver": drv, "old": o, "new": n, "flagged": []}
        if same_mesh:
            # "dropped" joins the gated totals when the run carries the
            # nemesis observables (ops/round_metrics churn columns),
            # "value_conv_final" when it carries a CRDT payload,
            # "log_conv_final" when it carries a replicated-log
            # payload, "txn_conv_final" when it carries the
            # LWW-register payload — absent keys fail the isinstance
            # guard and are skipped
            for key in ("newly", "dup", "msgs", "bytes", "dropped",
                        "value_conv_final", "log_conv_final",
                        "txn_conv_final"):
                a, b = o.get(key), n.get(key)
                if not isinstance(a, (int, float)) \
                        or not isinstance(b, (int, float)):
                    continue
                lo, hi = sorted([abs(a), abs(b)])
                if hi > 0 and (lo == 0 or hi / max(lo, 1e-9)
                               > metrics_ratio):
                    row["flagged"].append(key)
                    flags.append(
                        f"round_metrics[{drv}].{key} drifted "
                        f"{a} -> {b} at the same device count "
                        f"({old['device_count']}) — seeded protocol "
                        "totals must be stable; this is a semantic "
                        "change, not noise")
        else:
            notes.append(
                f"round_metrics[{drv}]: device counts differ "
                f"({old.get('device_count')} vs "
                f"{new.get('device_count')}) — protocol totals "
                "reported, not gated (stratification and padding are "
                "mesh-dependent)")
        metric_rows.append(row)

    # serving legs join informationally — rps/p50/p95/p99 deltas are
    # carried for the reader but NEVER produce a flag (walls never
    # gate; a latency number under a thread harness is host-load
    # noise, and the real gates — parity, all-warm — live in the
    # capture's own gate events)
    serving_rows = []
    for leg in sorted(set(old.get("serving") or {})
                      | set(new.get("serving") or {})):
        o = (old.get("serving") or {}).get(leg)
        n = (new.get("serving") or {}).get(leg)
        if o is None or n is None:
            notes.append(f"serving[{leg}]: only in "
                         f"{'new' if o is None else 'old'} run — "
                         "reported, not gated")
            continue
        serving_rows.append({"leg": leg, "old": o, "new": n})

    # trace-join summaries carry the same never-gate contract as the
    # serving legs: waterfall quantiles are host-load-shaped walls
    trace_row = None
    if old.get("traces") or new.get("traces"):
        if old.get("traces") and new.get("traces"):
            trace_row = {"old": old["traces"], "new": new["traces"]}
        else:
            notes.append("trace_join: only in "
                         f"{'new' if not old.get('traces') else 'old'} "
                         "run — reported, not gated")

    return {"rows": rows, "metric_rows": metric_rows, "flags": flags,
            "notes": notes, "drift": drift,
            "serving_rows": serving_rows, "trace_row": trace_row}


def _fmt(v):
    if v is None:
        return "—"
    if isinstance(v, float):
        return f"{v:.1f}"
    return str(v)


def render(old, new, d):
    """The diff as doc-ready markdown."""
    out = ["# Ledger diff", ""]
    for tag, run in (("old", old), ("new", new)):
        out.append(f"- {tag}: run `{run.get('run_id')}` captured "
                   f"{run.get('captured')} at commit "
                   f"`{(run.get('git_commit') or 'unknown')[:12]}`, "
                   f"{run.get('device_count')} device(s)")
    dr = d["drift"]
    out.append(f"- median drift divided out of the wall ratios: "
               f"steady_ms {dr['steady_ms']:.2f}x, "
               f"first_ms {dr['first_ms']:.2f}x")
    out.append("")
    if d["rows"]:
        out.append("| family | steady old→new (ms) | first old→new (ms)"
                   " | verdict | budget_ms | flag |")
        out.append("|---|---|---|---|---|---|")
        for r in d["rows"]:
            verdict = (r["verdict_old"] if r["verdict_old"]
                       == r["verdict_new"]
                       else f"{r['verdict_old']}→{r['verdict_new']}")
            flag = ("REGRESSED" if (r.get("steady_flag")
                                    or r.get("first_flag")
                                    or r.get("budget_flag")) else "ok")
            out.append(
                f"| {r['family']} "
                f"| {_fmt(r['steady_old'])} → {_fmt(r['steady_new'])} "
                f"| {_fmt(r['first_old'])} → {_fmt(r['first_new'])} "
                f"| {verdict or '—'} | {_fmt(r['budget_ms'])} "
                f"| {flag} |")
        out.append("")
    if d["metric_rows"]:
        out.append("## Round-metric totals")
        out.append("")
        # the dropped column only renders when some run carries the
        # nemesis observables (churn schedules, ops/nemesis)
        nem = any(r["old"].get("dropped") is not None
                  or r["new"].get("dropped") is not None
                  for r in d["metric_rows"])
        keys = ("rounds", "newly", "dup", "msgs", "bytes") \
            + (("dropped",) if nem else ())
        out.append("| driver | " + " | ".join(
            f"{k} old→new" for k in keys) + " | flagged |")
        out.append("|---" * (len(keys) + 2) + "|")
        for r in d["metric_rows"]:
            o, n = r["old"], r["new"]
            cells = [f"{_fmt(o.get(k))} → {_fmt(n.get(k))}"
                     for k in keys]
            out.append(f"| {r['driver']} | " + " | ".join(cells)
                       + f" | {', '.join(r['flagged']) or '—'} |")
        out.append("")
    if d.get("serving_rows"):
        out.append("## Serving legs (informational — walls never gate)")
        out.append("")
        out.append("| leg | devices | rps old→new | p50 old→new (ms) "
                   "| p95 old→new (ms) | p99 old→new (ms) |")
        out.append("|---|---|---|---|---|---|")
        for r in d["serving_rows"]:
            o, n = r["old"], r["new"]
            devs = (str(o.get("devices")) if o.get("devices")
                    == n.get("devices")
                    else f"{o.get('devices')}→{n.get('devices')}")
            out.append(
                f"| {r['leg']} | {devs} "
                f"| {_fmt(o.get('rps'))} → {_fmt(n.get('rps'))} "
                f"| {_fmt(o.get('p50_ms'))} → {_fmt(n.get('p50_ms'))} "
                f"| {_fmt(o.get('p95_ms'))} → {_fmt(n.get('p95_ms'))} "
                f"| {_fmt(o.get('p99_ms'))} → {_fmt(n.get('p99_ms'))} |")
        out.append("")
    if d.get("trace_row"):
        o, n = d["trace_row"]["old"], d["trace_row"]["new"]
        out.append("## Request traces (informational — never gate)")
        out.append("")
        out.append("| traces old→new | complete | replayed | expired "
                   "| wall p50 (ms) | wall p99 (ms) |")
        out.append("|---|---|---|---|---|---|")
        out.append(
            f"| {_fmt(o.get('traces'))} → {_fmt(n.get('traces'))} "
            f"| {_fmt(o.get('complete'))} → {_fmt(n.get('complete'))} "
            f"| {_fmt(o.get('replayed'))} → {_fmt(n.get('replayed'))} "
            f"| {_fmt(o.get('expired'))} → {_fmt(n.get('expired'))} "
            f"| {_fmt(o.get('wall_p50_ms'))} → "
            f"{_fmt(n.get('wall_p50_ms'))} "
            f"| {_fmt(o.get('wall_p99_ms'))} → "
            f"{_fmt(n.get('wall_p99_ms'))} |")
        out.append("")
    if d["flags"]:
        out.append("## Regressions flagged")
        out.append("")
        out.extend(f"- **{f}**" for f in d["flags"])
        out.append("")
    if d["notes"]:
        out.append("## Join notes")
        out.append("")
        out.extend(f"- {nt}" for nt in d["notes"])
        out.append("")
    out.append(f"Verdict: {'REGRESSED (' + str(len(d['flags'])) + ')' if d['flags'] else 'clean'}.")
    return "\n".join(out)


def render_protocol_metrics(events):
    """The "Protocol metrics" markdown section for a single run's
    ``round_metrics`` events (embedded by tools/telemetry_report.py) —
    the per-driver epidemic read-out: rounds, newly/dup/msgs/bytes
    totals, and the final per-shard coverage-front spread.  Returns []
    when the run carries no round metrics (pre-round-metrics
    ledgers)."""
    last = dict(_indexed_metric_events(events))
    if not last:
        return []
    # the dropped column renders only when some driver ran a nemesis
    # schedule (ops/round_metrics churn observables)
    nem = any((e.get("totals") or {}).get("dropped") is not None
              for e in last.values())
    out = ["## Protocol metrics (per-driver round totals)", ""]
    out.append("| driver | rounds | shards | newly | dup (est) | msgs "
               "| bytes/dev" + (" | dropped" if nem else "")
               + " | front min..max |")
    out.append("|---" * (8 + (1 if nem else 0)) + "|")
    for drv in sorted(last):
        e = last[drv]
        t = e.get("totals") or {}
        ff = e.get("front_final") or []
        spread = (f"{min(ff):.3f}..{max(ff):.3f}" if ff else "—")
        dropped = f"| {_fmt(t.get('dropped'))} " if nem else ""
        out.append(f"| {drv} | {e.get('rounds')} | {e.get('shards')} "
                   f"| {_fmt(t.get('newly'))} | {_fmt(t.get('dup'))} "
                   f"| {_fmt(t.get('msgs'))} | {_fmt(t.get('bytes'))} "
                   f"{dropped}| {spread} |")
    out.append("")
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("old", help="baseline ledger (e.g. the committed "
                                "artifacts/ledger_dryrun_*.jsonl)")
    ap.add_argument("new", help="candidate ledger (a fresh run)")
    ap.add_argument("--run-old", default="last",
                    help="run of OLD to use: run id, 'first' or 'last'")
    ap.add_argument("--run-new", default="last",
                    help="run of NEW to use: run id, 'first' or 'last'")
    ap.add_argument("--ratio", type=float, default=1.8,
                    help="wall ratio that flags (with the abs floor)")
    ap.add_argument("--steady-floor-ms", type=float, default=50.0)
    ap.add_argument("--first-floor-ms", type=float, default=250.0)
    ap.add_argument("--metrics-ratio", type=float, default=1.05,
                    help="protocol-total ratio that flags at equal "
                         "device counts")
    ap.add_argument("-o", "--out", default=None,
                    help="write the markdown report here too")
    args = ap.parse_args(argv)

    old = extract(select_run(args.old, args.run_old))
    new = extract(select_run(args.new, args.run_new))
    d = diff(old, new, ratio=args.ratio,
             steady_floor_ms=args.steady_floor_ms,
             first_floor_ms=args.first_floor_ms,
             metrics_ratio=args.metrics_ratio)
    doc = render(old, new, d)
    print(doc)
    if args.out:
        with open(args.out, "w") as f:
            f.write(doc + "\n")
    return 1 if d["flags"] else 0


if __name__ == "__main__":
    sys.exit(main())
