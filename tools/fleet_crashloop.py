#!/usr/bin/env python
"""Fleet crashloop: the nemesis pointed at the serving fleet itself.

tools/crashloop.py proved the simulator survives SIGKILLs of its own
process; this tool applies the same discipline one layer up, to the
REPLICATED SERVING fleet (rpc/router + N sidecar replicas,
docs/SERVING.md "Fleet"): it drives the load-harness request mix
through the fronting router from concurrent client threads, SIGKILLs K
replicas at seeded mid-load acked-count thresholds, respawns each one,
and gates the fleet contract:

  * **zero acked-request loss** — every request in the mix is acked
    with a valid reply despite the kills (the router re-dispatches
    in-flight requests to survivors; no client ever sees a transport
    error);
  * **per-request bitwise reply parity vs solo dispatch** — each
    fleet reply's curve / msgs / coverage / rounds equal an in-process
    ``run_simulation`` of the same payload (requests are deterministic
    pure functions of their payload, so failover replay cannot fork a
    trajectory);
  * **failover-visible ledger events** — one ``kill`` event per
    SIGKILL plus the router's ``replica_down`` / ``failover`` /
    ``replica_up`` / ``control_catchup`` flight-record (the respawned
    replica catches its config epoch up from the survivors' gossip,
    ops/logs control plane);
  * **recovery to full capacity** — every killed replica is respawned
    and re-admitted by the probe hysteresis, ending at N healthy.

The committed record is ``artifacts/ledger_fleet_r18.jsonl``
(provenance-stamped; tools/validate_artifacts.py refuses any
``*fleet*``/``*router*``/``*failover*`` artifact without provenance),
re-asserted by a tier-1 pin (tests/test_router.py) so it can never
rot.

    python tools/fleet_crashloop.py          # committed-record config:
        # 3 replicas, 48 requests, 2 seeded mid-load SIGKILLs ->
        # artifacts/ledger_fleet_r18.jsonl
    python tools/fleet_crashloop.py --smoke --out /tmp/fleet.jsonl

Replica children default to JAX_PLATFORMS=cpu (N replica processes
cannot share one TPU; ``--replica-platform ''`` inherits the ambient
pin on a multi-chip host) and share one compile-cache dir so a
respawned replica starts warm from its predecessors' executables.
Runs on the hermetic CPU tier by design: the failover contract is a
bitwise-trajectory structure, not a chip rate.
"""

import argparse
import json
import os
import random
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from load_harness import (compare_replies, distinct_requests,  # noqa: E402
                          request_mix)

DEFAULT_OUT = os.path.join(REPO, "artifacts", "ledger_fleet_r18.jsonl")


def solo_references(requests):
    """In-process solo dispatch of every request (the parity targets).
    ``run_simulation`` is the same entry point a ``--no-batching``
    sidecar runs per RPC, and the mix carries ``curve=True`` so the
    fixed-scan batched semantics equal the solo numbers byte for byte
    (the PR 9 admission contract, pinned on this exact mix by the
    committed serving record)."""
    from gossip_tpu.backend import request_to_args, run_simulation
    refs = []
    for req in requests:
        refs.append(run_simulation(**request_to_args(dict(req)))
                    .to_dict())
    return refs


def kill_thresholds(kills: int, total: int, seed: int):
    """One seeded acked-count threshold per equal slice of the middle
    of the run — kills land MID-load by construction (never before the
    first ack, never after the last), spread across the run instead of
    clustering (the crashloop stratified-draw discipline)."""
    rng = random.Random(seed)
    lo, hi = max(1, total // 5), max(2, (4 * total) // 5)
    pool = []
    for i in range(kills):
        s0 = lo + (hi - lo) * i // kills
        s1 = max(s0 + 1, lo + (hi - lo) * (i + 1) // kills)
        pool.append(rng.randrange(s0, s1))
    return sorted(pool), rng


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--kills", type=int, default=2,
                    help="seeded mid-load replica SIGKILLs (the "
                         "committed record carries K=2 on 3 replicas)")
    ap.add_argument("--kill-seed", type=int, default=18,
                    help="seeds the kill thresholds and victim draws "
                         "(a failing sequence replays exactly)")
    ap.add_argument("--n", type=int, default=256)
    ap.add_argument("--rounds", type=int, default=16)
    ap.add_argument("--repeats", type=int, default=12,
                    help="repeats of the 4-shape load-harness mix")
    ap.add_argument("--workers", type=int, default=12)
    ap.add_argument("--timeout-s", type=float, default=300.0,
                    help="per-request client deadline (bounds queue "
                         "wait + run + failover end to end)")
    ap.add_argument("--probe-interval-ms", type=float, default=200.0)
    ap.add_argument("--up-after", type=int, default=3)
    ap.add_argument("--replica-platform", default="cpu",
                    help="JAX_PLATFORMS pin for replica children "
                         "('' inherits the ambient platform)")
    ap.add_argument("--workdir", default=None,
                    help="replica log/cache scratch dir (default: a "
                         "fresh temp dir)")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny live fleet: 2 replicas, 1 kill, 8 "
                         "requests (every gate still enforced)")
    ap.add_argument("--out", default=None,
                    help="ledger path (default: the committed record "
                         "path, '.smoke'-infixed under --smoke — the "
                         "hw_refresh rehearsal convention)")
    a = ap.parse_args(argv)
    if a.out is None:
        a.out = (DEFAULT_OUT.replace(".jsonl", ".smoke.jsonl")
                 if a.smoke else DEFAULT_OUT)
    if a.smoke:
        a.replicas = min(a.replicas, 2)
        a.kills = min(a.kills, 1)
        a.repeats = min(a.repeats, 2)
        a.workers = min(a.workers, 4)
        a.n = min(a.n, 128)
        a.rounds = min(a.rounds, 8)

    if a.workdir is None:
        import tempfile
        a.workdir = tempfile.mkdtemp(prefix="fleet_crashloop_")
    os.makedirs(a.workdir, exist_ok=True)

    from gossip_tpu.config import FleetConfig
    from gossip_tpu.rpc.router import Fleet, fleet_env
    from gossip_tpu.rpc.sidecar import SidecarClient
    from gossip_tpu.utils import telemetry

    led = telemetry.Ledger(a.out)
    prev = telemetry.activate(led)   # router events land in this file
    fleet = None
    try:
        led.record_runtime()
        requests = request_mix(n=a.n, rounds=a.rounds,
                               repeats=a.repeats)
        total = len(requests)
        thresholds, rng = kill_thresholds(a.kills, total, a.kill_seed)
        led.event("config", replicas=a.replicas, kills=a.kills,
                  kill_seed=a.kill_seed, kill_thresholds=thresholds,
                  requests=total, workers=a.workers, n=a.n,
                  rounds=a.rounds, smoke=bool(a.smoke))

        # ---- solo parity references (in-process, unmeasured) --------
        t0 = time.perf_counter()
        refs = solo_references(requests)
        led.event("solo_refs_done",
                  wall_s=round(time.perf_counter() - t0, 3),
                  distinct=len({json.dumps(r, sort_keys=True)
                                for r in requests}))

        # ---- the fleet ----------------------------------------------
        cfg = FleetConfig(replicas=a.replicas,
                          probe_interval_ms=a.probe_interval_ms,
                          up_after=a.up_after,
                          max_inflight=max(8, a.workers))
        env = fleet_env(
            compile_cache_dir=os.path.join(a.workdir, "cache"),
            platform=a.replica_platform or None)
        fleet = Fleet(cfg=cfg, workdir=a.workdir, env=env,
                      max_workers=a.workers + 4)
        if not fleet.router.wait_healthy(a.replicas, timeout_s=60):
            raise RuntimeError("fleet never reached full health at "
                               "startup")
        # warm each replica DIRECTLY (the router would steer all
        # serial warmup at one replica): one pass of the distinct
        # shapes per replica; the shared cache dir serves replicas
        # 1..N-1 (and every respawn) from replica 0's compiles
        t0 = time.perf_counter()
        distinct = distinct_requests(requests)
        for r in fleet.router.replicas:
            c = SidecarClient(r.address, max_attempts=1)
            for req in distinct:
                c.run(timeout=a.timeout_s, **req)
            c.close()
        led.event("warmup_done",
                  wall_s=round(time.perf_counter() - t0, 3),
                  distinct=len(distinct))

        # ---- measured run: concurrent load + seeded kills -----------
        replies = [None] * total
        errors = []
        acked = {"count": 0}
        cursor = {"i": 0}
        lock = threading.Lock()

        def worker():
            client = SidecarClient(fleet.address, max_attempts=1)
            while True:
                with lock:
                    i = cursor["i"]
                    if i >= total:
                        break
                    cursor["i"] = i + 1
                try:
                    replies[i] = client.run(timeout=a.timeout_s,
                                            **requests[i])
                    with lock:
                        acked["count"] += 1
                except Exception as e:
                    with lock:
                        errors.append(
                            f"req {i}: {type(e).__name__}: "
                            f"{str(e).splitlines()[0][:200]}")
            client.close()

        led.event("load_phase", phase="measure_start")
        t0 = time.perf_counter()
        threads = [threading.Thread(target=worker)
                   for _ in range(a.workers)]
        for t in threads:
            t.start()
        # the killer: poll the acked counter, SIGKILL at each seeded
        # threshold, respawn immediately (the probe hysteresis + the
        # control-plane catchup re-admit it)
        kills_done = 0
        kill_acked = []
        for threshold in thresholds:
            while True:
                with lock:
                    now_acked = acked["count"]
                    done = cursor["i"] >= total
                if now_acked >= threshold:
                    break
                if done and not any(t.is_alive() for t in threads):
                    break
                time.sleep(0.002)
            with lock:
                now_acked = acked["count"]
            if now_acked >= total:
                led.event("kill_vacuous", threshold=threshold,
                          acked=now_acked)
                break      # nothing left mid-load to interrupt
            # draw the victim from replicas that are HEALTHY (in
            # rotation) with a live process: a just-respawned replica
            # still awaiting re-admission has nothing in flight to
            # interrupt, and killing it would emit no replica_down
            # (it already is down) — a seed-dependent verdict flake
            live = [i for i, r in enumerate(fleet.router.replicas)
                    if r.proc is not None and r.proc.poll() is None
                    and r.healthy]
            if not live:
                led.event("kill_skipped", threshold=threshold,
                          reason="no healthy replica to interrupt")
                continue
            victim = rng.choice(live)
            pid = fleet.kill(victim)
            kills_done += 1
            kill_acked.append(now_acked)
            led.event("kill", seq=kills_done, replica=victim, pid=pid,
                      threshold=threshold, acked=now_acked,
                      run_id=led.run_id)
            addr = fleet.restart(victim)
            led.event("respawn", replica=victim, address=addr)
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        led.event("load_phase", phase="measure_end",
                  wall_s=round(wall, 3),
                  rps=round(total / wall, 2) if wall else None)

        # ---- recovery to full capacity ------------------------------
        recovered = fleet.router.wait_healthy(a.replicas,
                                              timeout_s=120)
        stats = fleet.router.stats()
        led.event("recovered", ok=recovered, **stats)

        # ---- verdict ------------------------------------------------
        problems = list(errors)
        if kills_done < a.kills:
            problems.append(f"only {kills_done}/{a.kills} kills "
                            "landed (raise --repeats)")
        for k, at in enumerate(kill_acked):
            if not 0 < at < total:
                problems.append(f"kill {k + 1} landed at acked={at} "
                                f"of {total} — not mid-load")
        mismatches = compare_replies(replies, refs)
        for m in mismatches[:10]:
            led.event("parity_mismatch", detail=m)
        if mismatches:
            problems.append(f"{len(mismatches)} replies differ from "
                            "solo dispatch")
        if not recovered:
            problems.append(
                f"fleet never recovered to {a.replicas} healthy "
                f"replicas (healthy={stats['healthy']})")
        events = telemetry.load_ledger(a.out, run=led.run_id)

        def count(kind):
            return sum(1 for e in events if e.get("ev") == kind)
        if count("replica_down") < kills_done:
            problems.append("fewer replica_down events than kills — "
                            "the failover path was not exercised")
        if kills_done and count("failover") < 1:
            problems.append("no failover event: no in-flight request "
                            "was ever re-dispatched")
        if count("replica_up") < kills_done + a.replicas:
            problems.append("fewer replica_up events than "
                            "kills + initial admissions")
        if count("control_catchup") < kills_done:
            problems.append("a respawned replica never caught its "
                            "config epoch up from gossip")
        led.event("verdict", ok=not problems, kills=kills_done,
                  kill_acked=kill_acked, requests=total,
                  acked=acked["count"], errors=len(errors),
                  zero_acked_loss=not errors
                  and acked["count"] == total,
                  bitwise_equal=not mismatches,
                  mismatches=len(mismatches),
                  failovers=stats["failovers"],
                  recovered_full_capacity=recovered,
                  healthy=stats["healthy"], epochs=stats["epochs"],
                  problems=problems)
        if problems:
            for p in problems:
                print(f"FLEET CRASHLOOP FAIL: {p}", file=sys.stderr)
            return 1
        print(json.dumps({"ok": True, "kills": kills_done,
                          "requests": total, "acked": acked["count"],
                          "bitwise_equal": True,
                          "failovers": stats["failovers"],
                          "healthy": stats["healthy"],
                          "epochs": stats["epochs"],
                          "ledger": a.out}))
        return 0
    finally:
        if fleet is not None:
            fleet.close()
        telemetry.activate(prev)
        led.close()


if __name__ == "__main__":
    sys.exit(main())
