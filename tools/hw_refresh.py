#!/usr/bin/env python
"""One-shot hardware refresh: every measurement the rounds owe the chip.

Run when the axon tunnel is healthy (probe first — see
memory: a wedged tunnel hangs any jax init; tools/tunnel_watchdog.py
probes on a schedule and launches this script at the first healthy
window).  The outer timeout must cover the sum of ALL per-step
subprocess timeouts at their worst; ``worst_case_budget_s()`` below
computes it from the same constants the steps use (at the default
GOSSIP_BENCH_PROBE_ATTEMPTS=3 it is ~2100 (swim A/B) + 1500 (kernel
numbers) + 1200 (mr) + 900 (prng) + 1200 (fused sweep) + 1200
(roofline) + 2400 (sweep) + 1800 (swim ablation) + 2700 (ensembles) +
~6020 (bench worst case) + 2400 (pallas tests) = ~23,420 s):

    timeout 24000 python tools/hw_refresh.py      # default attempts
    python tools/hw_refresh.py --smoke            # CPU-scale rehearsal

``--smoke`` runs the SAME eleven-step pipeline at CPU scale on the
hermetic env (plugin disarmed, 8 virtual devices, interpreter-mode
kernels, sweep --scale 0.002, single fast bench probe) writing
``.smoke``-infixed artifacts — a rehearsal of every subprocess,
timeout, merge, and artifact path, runnable while the tunnel is down,
so the real window is never burned by a plumbing bug.

Steps (each prints a tagged JSON line; failures don't stop later steps;
ordered by VERDICT r4 priority so a short window lands the most
important captures first):
  1. SWIM dissemination A/B (sort vs pack) on the BASELINE-1M shape
     -> artifacts/swim_diss_ab_r05.json  (VERDICT r4 task 1a)
  2. bench.py headline
  3. PERF.md interactive-provenance kernel numbers re-measured
     -> artifacts/kernel_numbers_r05.json  (task 1b)
  4. staged big-table MR kernel validation at 10M x 32 rumors
     (post-padding variant) + per-round timing
  5. hardware-PRNG digest of the plane-sharded fused round
  5b. fused churn sweep: K mixed fault scenarios through ONE fused
     executable, solo-recompile vs warm ratio on real Mosaic kernels
     -> artifacts/ledger_fused_sweep_r17.jsonl (fused-operand PR)
  5c. scale planner: the streamed bit-plane tiling record (N = 2^20
     forced to >= 4 tiles, bitwise + coverage + memory-prediction
     gates), and on a real TPU backend the 100M-node --full-scale leg
     planned against the DETECTED chip/HBM/slice topology
     -> artifacts/ledger_scale_r20.jsonl (scale-planner PR)
  6. roofline: utilization vs first-principles floors, both fused
     layouts -> artifacts/roofline_r05.json  (task 3)
  7. the five BASELINE configs at full scale, SWIM row under the
     arbitrated A/B winner -> artifacts/baseline_sweep_r05.jsonl
  8. SWIM steady-state ms/round decomposition by component stubbing
     -> artifacts/swim_steady_ablation_r05.json  (task 4)
  9. ensemble surface on hardware via the public CLI
     -> artifacts/ensembles_r05.json  (task 6)
 10. TPU-only pallas statistics tests
     -> artifacts/tpu_pallas_tests_r05.txt

All step lines are also collected into artifacts/hw_refresh_r05.json.
Afterwards update README.md's hardware table (tools/readme_table.py)
and docs/PERF.md's pending numbers from the recorded lines.
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

MR_TIMEOUT_S = 1200
PRNG_TIMEOUT_S = 900
FUSED_SWEEP_TIMEOUT_S = 1200
SWEEP_TIMEOUT_S = 2400
TESTS_TIMEOUT_S = 2400
BENCH_SLACK_S = 200


def swim_ab_budget_s():
    """swim_diss_ab.py's self-computed worst case plus slack — derived
    from the child's own constants so this budget can't drift below
    what the child needs to run its own group-kill (killing it early
    would orphan a live TPU client on the single-client tunnel)."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    try:
        import swim_diss_ab
    finally:
        sys.path.pop(0)
    return swim_diss_ab.worst_case_budget_s() + 120

# --smoke: the full pipeline at CPU scale on the hermetic env — a
# REHEARSAL of every subprocess/plumbing/artifact path, so the one
# healthy tunnel window is never burned by a plumbing bug (round 2's
# capture failed exactly that way).  Smoke artifacts carry a .smoke
# infix and never touch the real r05 names.
SMOKE = False


def _art(name):
    if SMOKE:
        stem, dot, ext = name.rpartition(".")
        name = f"{stem}.smoke.{ext}" if dot else name + ".smoke"
    return os.path.join(REPO, "artifacts", name)


def summary_path():
    return _art("hw_refresh_r05.json")


_LEDGER = None


def _ledger():
    """The refresh run's flight recorder (utils/telemetry), opened
    lazily AFTER --smoke has been parsed (the path is smoke-infixed).
    Step subprocesses inherit the same file via GOSSIP_TELEMETRY
    (_body_env), so a window that closes mid-step still leaves one
    mechanically readable timeline: provenance, per-step spans (start
    fsynced before the subprocess launches), step verdict events, and
    whatever the children recorded before the kill."""
    global _LEDGER
    if _LEDGER is None:
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        try:
            from _telemetry import open_ledger
        finally:
            sys.path.pop(0)
        _LEDGER = open_ledger(_art("ledger_hw_refresh.jsonl"))
    return _LEDGER


def _load_bench():
    # single-source loader (tools/_bench.py) — lazy so importing this
    # module never pays the bench load
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    try:
        from _bench import load_bench
    finally:
        sys.path.pop(0)
    return load_bench()


def bench_budget_s():
    """bench.py's self-computed worst case plus this script's slack —
    the ONE place the bench step's timeout is defined."""
    return _load_bench().worst_case_budget_s() + BENCH_SLACK_S


def worst_case_budget_s():
    """Sum of every per-step subprocess timeout, so the recommended outer
    ``timeout`` can't silently drift below what a fully wedged run needs
    (bench's own worst case is computed by bench.py from its probe/body
    constants)."""
    return (swim_ab_budget_s() + KERNEL_NUMBERS_TIMEOUT_S + MR_TIMEOUT_S
            + PRNG_TIMEOUT_S + FUSED_SWEEP_TIMEOUT_S
            + SCALE_TIMEOUT_S + FULL_SCALE_TIMEOUT_S + COST_TIMEOUT_S
            + FLEET_TIMEOUT_S + ROOFLINE_TIMEOUT_S + SWEEP_TIMEOUT_S
            + SWIM_ABLATION_TIMEOUT_S + ENSEMBLES_TIMEOUT_S
            + bench_budget_s() + TESTS_TIMEOUT_S)


def load_summary():
    """Prior runs' step lines, keyed by step name — a retry must MERGE
    with these, never clobber a green result captured in an earlier
    healthy window."""
    try:
        with open(summary_path()) as f:
            return {r["step"]: r for r in json.load(f)}
    except (OSError, ValueError, KeyError, TypeError):
        return {}


_SUMMARY = load_summary()


def step(tag, fn):
    """Run one step; record its line in the merged summary.  Returns
    ``True`` (green), ``False`` (failed), or ``"timeout"`` — the
    subprocess-overran-its-budget case, which on the single-client axon
    tunnel is the wedge signature: the caller should stop burning the
    remaining steps' timeouts against a dead tunnel."""
    led = _ledger()     # lazy init (file open + git rev-parse) must not
    t0 = time.time()    # bill its cost to the first step's wall_s
    try:
        with led.span(tag, step=tag):
            out = fn()
        line = {"step": tag, "ok": True,
                "wall_s": round(time.time() - t0, 1), "result": out}
    except subprocess.TimeoutExpired as e:
        line = {"step": tag, "ok": False, "timed_out": True,
                "wall_s": round(time.time() - t0, 1),
                "error": f"TimeoutExpired: {e}"[:500]}
    except WedgeDetected as e:
        # child-diagnosed wedge (rc 2 convention): same abort semantics
        # as an actual budget overrun
        line = {"step": tag, "ok": False, "timed_out": True,
                "wall_s": round(time.time() - t0, 1),
                "error": f"WedgeDetected: {e}"[:500]}
    except Exception as e:  # keep going; later steps still run
        line = {"step": tag, "ok": False,
                "wall_s": round(time.time() - t0, 1),
                "error": f"{type(e).__name__}: {e}"[:500]}
    print(json.dumps(line), flush=True)
    led.event("step", **line)
    # persist after EVERY step so an outer-timeout kill still leaves the
    # completed steps on disk as a committable artifact; a failed write
    # must not abort the remaining steps (stdout still carries the line)
    _SUMMARY[tag] = line
    try:
        with open(summary_path(), "w") as f:
            json.dump(list(_SUMMARY.values()), f, indent=1)
    except OSError as e:
        print(f"hw_refresh: summary write failed: {e}", file=sys.stderr)
    if line.get("timed_out"):
        return "timeout"
    return line["ok"]


def _mr_staged_body():
    """Runs in a SUBPROCESS: the axon tunnel is single-client, so the
    parent must never hold a jax TPU client while later steps spawn
    their own (they would hang on the busy tunnel)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from gossip_tpu.ops.pallas_round import (fused_multirumor_pull_round,
                                             init_multirumor_state)
    # smoke: tiny n on the CPU interpreter (stubbed PRNG — plumbing
    # rehearsal, not statistics; all_rumors_growing is reported, not
    # asserted, and is expected False under the degenerate stub)
    n = 128 * 8 if SMOKE else 10_000_000
    rounds = 4 if SMOKE else 20
    st = init_multirumor_state(n, 32)
    jax.block_until_ready(st.table)
    t0 = time.perf_counter()
    out = fused_multirumor_pull_round(st.table, jnp.int32(0), jnp.int32(1),
                                      n, 1, interpret=SMOKE)
    jax.block_until_ready(out)
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for r in range(2, rounds + 2):
        out = fused_multirumor_pull_round(out, jnp.int32(0), jnp.int32(r),
                                          n, 1, interpret=SMOKE)
    jax.block_until_ready(out)
    per_round_ms = (time.perf_counter() - t0) / rounds * 1e3
    flat = np.asarray(out).reshape(-1)[:n]
    counts = [int(((flat >> k) & np.uint32(1)).sum()) for k in range(32)]
    print(json.dumps({"compile_s": round(compile_s, 2),
                      "per_round_ms": round(per_round_ms, 3),
                      "rounds_run": rounds + 1,
                      f"mean_count_after_{rounds + 1}": sum(counts) / 32,
                      "all_rumors_growing": all(c > 64 for c in counts),
                      "smoke": SMOKE}))
    return 0


def _prng_body():
    """Subprocess: hardware-PRNG digest of the plane-sharded fused round
    (sharded_fused.assert_prng_invariant).  On the single-chip tunnel
    the all-equal assertion is trivial (one device) but the digest
    itself is the real hardware PRNG artifact; a multi-chip pod runs
    the same step and checks the zero-ICI same-stream invariant for
    real."""
    import jax
    import numpy as np

    from gossip_tpu.parallel.sharded_fused import (assert_prng_invariant,
                                                   make_plane_mesh)
    n_dev = len(jax.devices())
    mesh = make_plane_mesh(n_dev)
    d = assert_prng_invariant(128 * 8 if SMOKE else 128 * 64, mesh,
                              interpret=SMOKE)
    print(json.dumps({"devices": n_dev,
                      "digests": np.asarray(d).tolist(),
                      "smoke": SMOKE}))
    return 0


def _body_env():
    """Env for the step subprocesses.  Real runs keep the ambient TPU
    platform (plus the repo on PYTHONPATH for run-by-path imports); the
    smoke rehearsal must be fully hermetic — CPU platform, plugin
    disarmed, an 8-device virtual mesh — or a wedged tunnel would hang
    the rehearsal whose whole point is to run while the tunnel is down.
    """
    if not SMOKE:
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        return _share_ledger(env)
    env = _load_bench()._hermetic_cpu_env()
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    # conftest honors this var over JAX_PLATFORMS — an operator who has
    # it exported for hardware runs must not leak it into the rehearsal
    env.pop("GOSSIP_TPU_TEST_PLATFORM", None)
    return _share_ledger(env)


def _share_ledger(env):
    """Children append to the refresh ledger (one timeline per window;
    their own provenance lines carry distinct run ids)."""
    path = _ledger().path
    if path:
        env.setdefault("GOSSIP_TELEMETRY", path)
    return env


def _smoke_argv():
    return ["--smoke"] if SMOKE else []


class WedgeDetected(RuntimeError):
    """A step's child diagnosed the tunnel-wedge signature itself (the
    capture tools' rc 2 convention) — same meaning as the step blowing
    its subprocess budget: the window just closed, and every remaining
    step would deterministically burn its full budget against a dead
    tunnel.  step() maps this to the "timeout" abort like an actual
    TimeoutExpired."""


def swim_diss_ab():
    """Arbitrate the SWIM dissemination lowerings (sort control vs pack
    candidate) on the chip — VERDICT r4 task 1a.  Delegates to
    tools/swim_diss_ab.py (probe-first, per-impl fresh compile cache,
    group-kill on wedge); its rc 2 is the transient convention (tunnel
    re-wedged mid-A/B), surfaced as the wedge signature so the
    remaining steps abort and the watchdog retries at the next
    window."""
    p = subprocess.run([sys.executable,
                        os.path.join(REPO, "tools", "swim_diss_ab.py"),
                        *_smoke_argv()],
                       capture_output=True, text=True,
                       timeout=swim_ab_budget_s(), cwd=REPO,
                       env=_body_env())
    if p.returncode == 2:
        raise WedgeDetected("swim_diss_ab rc 2 (tunnel re-wedged "
                            "mid-A/B)\n" + (p.stderr or p.stdout)[-400:])
    if p.returncode != 0:
        raise RuntimeError(f"rc {p.returncode}\n"
                           + (p.stderr or p.stdout)[-400:])
    with open(_art("swim_diss_ab_r05.json")) as f:
        doc = json.load(f)
    return {"verdict": doc.get("verdict"),
            "trajectories_identical": doc.get("trajectories_identical"),
            "rows": [{k: r.get(k) for k in ("swim_diss", "wall_s",
                                            "compile_s", "steady_wall_s")}
                     for r in doc.get("rows", [])]}


def swim_diss_winner():
    """The arbitrated dissemination lowering from this round's committed
    A/B artifact (its explicit ``winner`` field — ONE definition, owned
    by swim_diss_ab.py), or None (CLI default) when no clean verdict
    exists — the sweep recapture below passes it through so the SWIM
    row is re-measured under the winner in the SAME window (VERDICT r4
    1a)."""
    try:
        with open(_art("swim_diss_ab_r05.json")) as f:
            doc = json.load(f)
        if not doc.get("trajectories_identical"):
            return None
        return doc.get("winner")
    except (OSError, ValueError):
        return None


STATICCHECK_TIMEOUT_S = 120    # pure-stdlib AST passes: seconds, no jax
KERNEL_NUMBERS_TIMEOUT_S = 1500
ROOFLINE_TIMEOUT_S = 1200
ENSEMBLES_TIMEOUT_S = 2700     # covers both sub-captures' own budgets
SWIM_ABLATION_TIMEOUT_S = 1800  # ~6 variants x ~130 s compile + timing


def _run_tool(script: str, timeout_s: int):
    """Run a capture tool (tools/<script>) and return ITS last stdout
    JSON line — the tool owns its artifact, smoke infixing, and summary
    keys (one definition, one file; hw_refresh never re-derives them).
    rc 2 is the capture-tool transient convention (a sub-run hit the
    wedge signature) and aborts the remaining steps via WedgeDetected."""
    p = subprocess.run([sys.executable,
                        os.path.join(REPO, "tools", script),
                        *_smoke_argv()],
                       capture_output=True, text=True,
                       timeout=timeout_s, cwd=REPO, env=_body_env())
    if p.returncode == 2:
        raise WedgeDetected(f"{script} rc 2 (wedge signature mid-run)\n"
                            + (p.stderr or p.stdout)[-400:])
    if p.returncode != 0:
        raise RuntimeError(f"rc {p.returncode}\n"
                           + (p.stderr or p.stdout)[-400:])
    return json.loads(p.stdout.strip().splitlines()[-1])


def kernel_numbers():
    """Re-measure docs/PERF.md's interactive-provenance kernel numbers
    (VERDICT r4 task 1b) — single-rumor ms/round, VMEM OOM ladder,
    topology build, fault-mask on-cost."""
    return _run_tool("kernel_numbers.py", KERNEL_NUMBERS_TIMEOUT_S)


def roofline():
    """Utilization vs first-principles floors for both fused layouts
    (VERDICT r4 task 3)."""
    return _run_tool("roofline.py", ROOFLINE_TIMEOUT_S)


def fused_churn_sweep():
    """K mixed nemesis scenarios — events, partition windows, drop
    ramps — through the plane-sharded fused engine ON THE CHIP: solo
    (per-scenario Mosaic kernel recompile, the pre-operand cost model)
    vs warm (one executable, schedule content as runtime operands) —
    tools/fused_sweep_capture.py.  This is the fused family's first
    real-hardware fault-scenario measurement; the committed r17 record
    is the CPU reference-lowering structure proof, and this leg
    refreshes the stale r06 CPU-fallback headline with Mosaic
    numbers."""
    return _run_tool("fused_sweep_capture.py", FUSED_SWEEP_TIMEOUT_S)


def staticcheck():
    """The AST invariant analyzer over the tree this capture runs from
    (tools/staticcheck.py): recompile-hazard lint, rpc lock
    discipline, convention gates — pure stdlib, CPU-only, seconds.
    Runs FIRST so a capture window never spends its budget measuring a
    tree whose serving invariants already regressed; it is also the
    one step a wedged tunnel cannot take down (no jax import)."""
    return _run_tool("staticcheck.py", STATICCHECK_TIMEOUT_S)


def _scale_leg(flag, timeout_s):
    """One gated scale_capture re-run (--full-scale / --multislice)
    into its own artifact, returning the leg's last stdout JSON line.
    rc 2 keeps the wedge-signature meaning; any other non-zero rc is
    the leg's own gate failing."""
    p = subprocess.run([sys.executable,
                        os.path.join(REPO, "tools",
                                     "scale_capture.py"),
                        flag, *_smoke_argv()],
                       capture_output=True, text=True,
                       timeout=timeout_s, cwd=REPO, env=_body_env())
    if p.returncode == 2:
        raise WedgeDetected(f"scale_capture {flag} rc 2\n"
                            + (p.stderr or p.stdout)[-400:])
    if p.returncode != 0:
        raise RuntimeError(f"scale_capture {flag} rc {p.returncode}\n"
                           + (p.stderr or p.stdout)[-400:])
    return json.loads(p.stdout.strip().splitlines()[-1])


def scale_plan():
    """The scale planner's streamed-tiling record on this host
    (tools/scale_capture.py): N = 2^20 forced to >= 4 streamed word-
    plane tiles through the three-stage pipeline, bitwise-vs-untiled +
    no-overlap-A/B + simulated-2-slice + coverage-1.0 +
    memory-prediction gates — the structural proof refreshed at the
    capture window.  On a real TPU backend the tool is then re-run
    with ``--full-scale`` (the 100M-node leg against the DETECTED
    chip/HBM/slice topology — gated on real HBM only, which is why the
    committed record stays the CPU structural proof until a window
    lands, ROADMAP item 3), and when the structural record reports
    more than one DCN slice, with ``--multislice`` too: the executor
    leg that fans the tile stream across the REAL slices."""
    line = _run_tool("scale_capture.py", SCALE_TIMEOUT_S)
    if line.get("backend") == "tpu":
        line["full_scale"] = _scale_leg("--full-scale",
                                        FULL_SCALE_TIMEOUT_S)
        if line.get("slices", 1) > 1:
            line["multislice"] = _scale_leg("--multislice",
                                            FULL_SCALE_TIMEOUT_S)
    return line


def cost_attribution():
    """The XLA cost & memory attribution record on this host
    (tools/cost_capture.py, docs/OBSERVABILITY.md "XLA cost & memory
    attribution"): one forced-miss compile per engine through the ONE
    chokepoint, every ``xla_compile`` event labeled + verdict-carrying
    with cost/memory fields populated-or-null, the cross-closure warm
    re-entry coming back a store HIT, and the packed budget
    cross-check green (measured peak bytes <= the planner's closed
    form at a forced >=4-tile plan).  On a TPU window the same tool
    attributes real HBM executables — the cost table the capacity
    plans cite then names hardware numbers, not the CPU structural
    proof."""
    return _run_tool("cost_capture.py", COST_TIMEOUT_S)


def byzantine_conv():
    """The byzantine-adversary convergence record on this host
    (tools/byzantine_capture.py, docs/ROBUSTNESS.md "Byzantine
    adversaries"): the mixed fail-stop + scripted-liar scenario with
    the defended arm converging EXACTLY on the honest eventual-alive
    set (integer count == denominator) while the undefended control
    arm provably diverges, plus bitwise 1-vs-4-device mesh parity.
    Integer arithmetic on honest-owned components, not a chip rate —
    but re-proven on whatever host the hardware captures run on."""
    return _run_tool("byzantine_capture.py", BYZ_TIMEOUT_S)


def fleet_failover():
    """The replicated serving fleet's crashloop on this host
    (tools/fleet_crashloop.py): the load mix through the fronting
    router, seeded mid-load replica SIGKILLs, zero acked-request loss
    + bitwise failover parity + recovery gates, refreshing the
    committed fleet record.  Replica children pin JAX_PLATFORMS=cpu by
    design — N replica processes cannot share one TPU, and the fleet
    contract is a bitwise-trajectory structure, not a chip rate — so
    this step certifies the serving layer survives its nemesis on the
    same host the hardware captures run on."""
    return _run_tool("fleet_crashloop.py", FLEET_TIMEOUT_S)


def request_trace():
    """The request-tracing record on this host
    (tools/trace_capture.py, docs/OBSERVABILITY.md "Request tracing &
    live metrics"): the traced load mix through the router, one seeded
    mid-load SIGKILL, every acked request joining to a COMPLETE
    waterfall (failover-replayed included), fleet-status seeing the
    kill and the recovery, and the post-recovery steady window gated
    zero-compile + zero-fsync via the Metrics counters.  The summary
    line is re-joined here to refresh the ATTRIBUTED slow-request
    exemplars (wall + dominant leg) alongside the committed ledger."""
    out = _run_tool("trace_capture.py", TRACE_TIMEOUT_S)
    import trace_report
    rows = trace_report.waterfalls(
        trace_report.load_events([out["ledger"]]))
    out["exemplars"] = trace_report.exemplars(rows, k=3)
    return out


def mesh_serving():
    """The mesh-sharded serving capture on this host
    (tools/load_harness.py --mesh-devices, docs/SERVING.md
    "Mesh-sharded replicas"): fixed-concurrency legs per
    devices-per-replica width, gated on bitwise reply parity and
    steady-all-warm.  On hosts with enough schedulable cores the
    >= --mesh-min-ratio device-scaling gate arms itself
    (``scaling_resolved`` in the gate event) — THIS step is where the
    committed meshserve record's scaling leg gets its real
    multi-core/multi-chip recapture; on a serial host the capture
    still certifies parity + warmth and ledgers the scaling leg as
    unresolved."""
    p = subprocess.run([sys.executable,
                        os.path.join(REPO, "tools", "load_harness.py"),
                        "--mesh-devices", "1,4",
                        "--out", _art("ledger_meshserve_r21.jsonl"),
                        *_smoke_argv()],
                       capture_output=True, text=True,
                       timeout=MESH_SERVING_TIMEOUT_S, cwd=REPO,
                       env=_body_env())
    if p.returncode == 2:
        raise WedgeDetected("load_harness rc 2 (wedge signature)\n"
                            + (p.stderr or p.stdout)[-400:])
    if p.returncode != 0:
        raise RuntimeError(f"rc {p.returncode}\n"
                           + (p.stderr or p.stdout)[-400:])
    return json.loads(p.stdout.strip().splitlines()[-1])


def ensembles():
    """The round-4 ensemble surface on hardware via the public CLI
    (VERDICT r4 task 6).  The tool merges sub-captures incrementally;
    a deterministic sub-capture failure (rc 1) keeps this pending for
    the watchdog's bounded retries, a wedge (rc 2) aborts the rest."""
    return _run_tool("ensemble_capture.py", ENSEMBLES_TIMEOUT_S)


def swim_steady_ablation():
    """Steady-state ms/round decomposition of the BASELINE SWIM shape
    (VERDICT r4 task 4: name the residual 374 ms/round's owner or the
    floor).  Merges variant rows across retries."""
    return _run_tool("swim_steady_ablation.py", SWIM_ABLATION_TIMEOUT_S)


def prng_invariant():
    p = subprocess.run([sys.executable, os.path.abspath(__file__),
                        "--prng-body", *_smoke_argv()],
                       capture_output=True, text=True,
                       timeout=PRNG_TIMEOUT_S, cwd=REPO, env=_body_env())
    if p.returncode != 0:
        raise RuntimeError((p.stderr or p.stdout)[-400:])
    return json.loads(p.stdout.strip().splitlines()[-1])


def mr_staged_10m():
    # run-by-path puts tools/ (not the repo root) on the child's
    # sys.path; gossip_tpu needs an explicit PYTHONPATH entry
    # (_body_env provides it both modes)
    p = subprocess.run([sys.executable, os.path.abspath(__file__),
                        "--mr-body", *_smoke_argv()],
                       capture_output=True, text=True,
                       timeout=MR_TIMEOUT_S, cwd=REPO, env=_body_env())
    if p.returncode != 0:
        raise RuntimeError((p.stderr or p.stdout)[-400:])
    return json.loads(p.stdout.strip().splitlines()[-1])


def _write_sweep_artifact(stdout):
    """Persist whatever config lines the sweep produced — a crash or
    timeout on config 5 must not discard 4 completed full-scale
    hardware measurements from a scarce healthy window.  MERGES with an
    existing artifact by config name (new rows win) so a retry that got
    less far can never clobber rows a fuller earlier attempt captured."""
    art = _art("baseline_sweep_r05.jsonl")
    if isinstance(stdout, bytes):
        stdout = stdout.decode(errors="replace")
    stdout = stdout or ""

    def rows_by_config(text):
        rows = {}
        for line in text.splitlines():
            try:
                r = json.loads(line)
                rows[r["config"]] = line
            except (ValueError, KeyError, TypeError):
                continue
        return rows

    new = rows_by_config(stdout)
    if new:
        merged = {}
        try:
            with open(art) as f:
                merged = rows_by_config(f.read())
        except OSError:
            pass
        merged.update(new)
        with open(art, "w") as f:
            f.write("\n".join(merged.values()) + "\n")
    return stdout


def baseline_sweep():
    try:
        # -u: the per-config JSONL lines must not die in the child's
        # block buffer when a timeout SIGKILLs it mid-sweep
        scale = "0.002" if SMOKE else "1.0"
        extra = ["--devices", "4"] if SMOKE else []
        # --no-compile-cache: the captured compile_s IS the canonical
        # cold number; the (default-on) persistent cache would silently
        # substitute a ~3 s warm compile on any host that ever built
        # these shapes before
        winner = swim_diss_winner()
        if winner:
            extra += ["--swim-diss", winner]
        elif not os.path.exists(_art("swim_diss_ab_r05.json")):
            # the SWIM row's whole point this round is re-measurement
            # under the ARBITRATED lowering (VERDICT r4 1a).  If the A/B
            # hasn't produced an artifact yet (step pending/transient),
            # a sweep run now would go green under the CLI default and
            # never be re-captured on retry (pending_steps skips green
            # steps) — so stay pending until the A/B lands.  A written
            # artifact with no winner (trajectory mismatch) is a real
            # verdict, and so is a recorded DETERMINISTIC A/B failure
            # (e.g. the candidate lowering crashing on the chip — rc 1,
            # no artifact): both proceed under the default rather than
            # blocking the five-config capture forever.
            ab = load_summary().get("swim_diss_ab", {})
            deterministic_ab_failure = (
                ab and not ab.get("ok") and not ab.get("timed_out")
                and "WedgeDetected" not in ab.get("error", ""))
            if not deterministic_ab_failure:
                raise RuntimeError(
                    "blocked: swim_diss_ab has no artifact yet; the "
                    "SWIM row must be captured under the arbitrated "
                    "lowering")
        p = subprocess.run([sys.executable, "-u", "-m", "gossip_tpu",
                            "sweep", "--scale", scale,
                            "--no-compile-cache", *extra],
                           capture_output=True, text=True,
                           timeout=SWEEP_TIMEOUT_S, cwd=REPO,
                           env=_body_env())
    except subprocess.TimeoutExpired as e:
        _write_sweep_artifact(e.stdout)
        raise
    out = _write_sweep_artifact(p.stdout)
    if p.returncode != 0:
        raise RuntimeError(p.stderr[-400:])
    rows = [json.loads(line) for line in out.splitlines() if line.strip()]
    return [{"config": r["config"], "rounds": r["rounds"],
             "coverage": round(r["coverage"], 4), "wall_s": r["wall_s"],
             "compile_s": r.get("meta", {}).get("compile_s"),
             "steady_wall_s": r.get("meta", {}).get("steady_wall_s"),
             "engine": r.get("meta", {}).get("engine")}
            for r in rows]


def bench():
    # must outlast bench.py's own worst case (probe retries + body +
    # hermetic retry) — computed by bench.py itself from the same
    # constants its loops use, so the budget can't drift.  Smoke: one
    # fast probe on the hermetic CPU env (exercises bench's whole
    # probe->body->one-JSON-line pipeline via its CPU fallback).
    # non-smoke deliberately keeps the ambient env untouched (bench owns
    # its own probe/fallback logic and never needed the PYTHONPATH help)
    if SMOKE:
        env = {**_body_env(), "GOSSIP_BENCH_PROBE_ATTEMPTS": "1"}
    else:
        env = _share_ledger(dict(os.environ))
    p = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                       capture_output=True, text=True,
                       timeout=bench_budget_s(), cwd=REPO, env=env)
    if p.returncode != 0:
        raise RuntimeError((p.stderr or p.stdout)[-400:])
    return json.loads(p.stdout.strip().splitlines()[-1])


def tpu_pallas_tests():
    art = _art("tpu_pallas_tests_r05.txt")
    # conftest pins tests to CPU unless this var points at the chip;
    # smoke keeps CPU (the TPU-only classes skip — the rehearsal proves
    # the pytest/artifact plumbing, the chip proves the statistics)
    env = (_body_env() if SMOKE
           else {**os.environ, "GOSSIP_TPU_TEST_PLATFORM": "axon"})

    def _text(x):
        return ("" if x is None else
                x if isinstance(x, str) else x.decode(errors="replace"))

    try:
        # -u for the same reason as the sweep: per-test progress must
        # survive a timeout SIGKILL for the partial artifact to exist
        p = subprocess.run([sys.executable, "-u", "-m", "pytest",
                            "tests/test_pallas.py",
                            "tests/test_pallas_round.py", "-q"],
                           capture_output=True, text=True,
                           timeout=TESTS_TIMEOUT_S, cwd=REPO, env=env)
    except subprocess.TimeoutExpired as e:
        with open(art, "w") as f:
            f.write(_text(e.stdout) + "\n--- TIMED OUT after "
                    f"{TESTS_TIMEOUT_S} s ---\n--- stderr ---\n"
                    + _text(e.stderr)[-2000:])
        raise
    with open(art, "w") as f:
        f.write(p.stdout + "\n--- stderr ---\n" + p.stderr[-2000:])
    tail = p.stdout.strip().splitlines()[-1] if p.stdout.strip() else ""
    if p.returncode != 0:
        raise RuntimeError(tail)
    return tail


# Priority order = VERDICT r4 task 1: the A/B arbitration first (it
# unblocks the SWIM default flip and the sweep recapture), then the
# scoreboard headline, then the cheap kernel validations, then the
# five-config sweep (which picks up the A/B winner), then the test tier.
# A window that closes mid-run lands the most important steps first;
# retries are incremental (pending steps only).
FLEET_TIMEOUT_S = 1200
TRACE_TIMEOUT_S = 1200          # traced crashloop + steady window
MESH_SERVING_TIMEOUT_S = 1200   # thousands of connections x 2 legs
SCALE_TIMEOUT_S = 1200          # structural record: ~2 min on CPU
FULL_SCALE_TIMEOUT_S = 3600     # the 100M leg owns a real window slot
COST_TIMEOUT_S = 900            # 7 tiny compiles + one forced-tile run
BYZ_TIMEOUT_S = 900             # 2 payload classes x 2 arms + parity

STEPS = [("staticcheck", staticcheck),
         ("swim_diss_ab", swim_diss_ab),
         ("bench", bench),
         ("kernel_numbers", kernel_numbers),
         ("mr_staged_10m", mr_staged_10m),
         ("prng_invariant", prng_invariant),
         ("fused_churn_sweep", fused_churn_sweep),
         ("byzantine_conv", byzantine_conv),
         ("scale_plan", scale_plan),
         ("cost_attribution", cost_attribution),
         ("fleet_failover", fleet_failover),
         ("request_trace", request_trace),
         ("mesh_serving", mesh_serving),
         ("roofline", roofline),
         ("baseline_sweep", baseline_sweep),
         ("swim_steady_ablation", swim_steady_ablation),
         ("ensembles", ensembles),
         ("tpu_pallas_tests", tpu_pallas_tests)]


def pending_steps():
    """Step names without a green line in the merged summary — what a
    retry should run instead of re-burning already-captured steps."""
    done = load_summary()
    return [t for t, _ in STEPS if not done.get(t, {}).get("ok")]


def main(only=None):
    """Exit code reports overall outcome so callers (tunnel_watchdog)
    can tell a captured refresh from a burned window: 0 = every
    requested step ok, 1 = partial (some landed), 2 = nothing
    succeeded.  ``only`` (or --steps a,b on the CLI) restricts to the
    named steps; a step TIMEOUT aborts the rest — on the single-client
    tunnel it means the window just closed, and each remaining step
    would deterministically burn its full budget against a wedged
    tunnel before the watchdog could resume probing."""
    if only is not None and not list(only):
        print(json.dumps({"nothing_pending": True}), flush=True)
        return 0
    _ledger().event("refresh_start", smoke=SMOKE,
                    steps=[t for t, _ in STEPS
                           if only is None or t in only])
    results = []
    for tag, fn in STEPS:
        if only is not None and tag not in only:
            continue
        r = step(tag, fn)
        results.append(r)
        if r == "timeout":
            print(json.dumps({"aborted_after": tag,
                              "reason": "step timeout = wedge signature; "
                                        "not burning remaining budgets"}),
                  flush=True)
            _ledger().event("refresh_abort", after=tag,
                            reason="step timeout = wedge signature")
            break
    oks = [r is True for r in results]
    return 0 if oks and all(oks) else (1 if any(oks) else 2)


if __name__ == "__main__":
    # Hand-rolled args (argparse would fight the --steps comma contract
    # callers already depend on), so REJECT anything unrecognized: a
    # typo'd or guessed flag (--help, --dry-run, ...) must print usage,
    # not silently launch a full hardware-refresh attempt against the
    # single-client tunnel.
    _known = {"--smoke", "--mr-body", "--prng-body", "--steps"}
    _args = sys.argv[1:]
    _bad = [a for i, a in enumerate(_args)
            if a not in _known and not (i > 0 and _args[i - 1] == "--steps")]
    if _bad:
        print(f"unrecognized args: {_bad}\n"
              "usage: hw_refresh.py [--smoke] [--steps a,b,...] "
              "[--mr-body|--prng-body]\n"
              "NO ARGS runs every pending hardware step (probe the "
              "tunnel first; see tools/tunnel_watchdog.py)",
              file=sys.stderr)
        sys.exit(2)
    if "--smoke" in sys.argv:
        SMOKE = True
        _SUMMARY = load_summary()   # re-key to the smoke summary path
    if "--mr-body" in sys.argv:
        sys.exit(_mr_staged_body())
    if "--prng-body" in sys.argv:
        sys.exit(_prng_body())
    only = None
    if "--steps" in sys.argv:
        idx = sys.argv.index("--steps") + 1
        if idx >= len(sys.argv):
            print("--steps needs a comma-separated value, e.g. "
                  "--steps bench,tpu_pallas_tests", file=sys.stderr)
            sys.exit(2)
        names = sys.argv[idx].split(",")
        known = {t for t, _ in STEPS}
        bad = [n for n in names if n and n not in known]
        if bad:
            print(f"unknown steps: {bad}; known: {sorted(known)}",
                  file=sys.stderr)
            sys.exit(2)
        only = [n for n in names if n]
    sys.exit(main(only))
