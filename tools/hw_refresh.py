#!/usr/bin/env python
"""One-shot hardware refresh: every measurement round 2 owes the chip.

Run when the axon tunnel is healthy (probe first — see
memory: a wedged tunnel hangs any jax init).  The outer timeout must
cover the sum of ALL per-step subprocess timeouts at their worst —
1200 (mr) + 2400 (sweep) + bench's worst case (~6020 s at the default
GOSSIP_BENCH_PROBE_ATTEMPTS=3; bench.worst_case_budget_s() gives the
exact number for other settings) + 2400 (pallas tests) ≈ 12,100 s:

    timeout 12600 python tools/hw_refresh.py      # default attempts

Steps (each prints a tagged JSON line; failures don't stop later steps):
  1. staged big-table MR kernel validation at 10M x 32 rumors
     (post-padding variant) + per-round timing
  2. the five BASELINE configs at full scale
     -> artifacts/baseline_sweep_r02b.jsonl
  3. bench.py headline
  4. TPU-only pallas statistics tests
     -> artifacts/tpu_pallas_tests_r02b.txt

Afterwards update README.md's hardware table and docs/PERF.md's pending
numbers from the printed lines.
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def step(tag, fn):
    t0 = time.time()
    try:
        out = fn()
        print(json.dumps({"step": tag, "ok": True,
                          "wall_s": round(time.time() - t0, 1),
                          "result": out}), flush=True)
    except Exception as e:  # keep going; later steps still run
        print(json.dumps({"step": tag, "ok": False,
                          "wall_s": round(time.time() - t0, 1),
                          "error": f"{type(e).__name__}: {e}"[:500]}),
              flush=True)


def _mr_staged_body():
    """Runs in a SUBPROCESS: the axon tunnel is single-client, so the
    parent must never hold a jax TPU client while later steps spawn
    their own (they would hang on the busy tunnel)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from gossip_tpu.ops.pallas_round import (fused_multirumor_pull_round,
                                             init_multirumor_state)
    n = 10_000_000
    st = init_multirumor_state(n, 32)
    jax.block_until_ready(st.table)
    t0 = time.perf_counter()
    out = fused_multirumor_pull_round(st.table, jnp.int32(0), jnp.int32(1),
                                      n, 1)
    jax.block_until_ready(out)
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for r in range(2, 22):
        out = fused_multirumor_pull_round(out, jnp.int32(0), jnp.int32(r),
                                          n, 1)
    jax.block_until_ready(out)
    per_round_ms = (time.perf_counter() - t0) / 20 * 1e3
    flat = np.asarray(out).reshape(-1)[:n]
    counts = [int(((flat >> k) & np.uint32(1)).sum()) for k in range(32)]
    print(json.dumps({"compile_s": round(compile_s, 2),
                      "per_round_ms": round(per_round_ms, 3),
                      "mean_count_after_21": sum(counts) / 32,
                      "all_rumors_growing": all(c > 64 for c in counts)}))
    return 0


def _prng_body():
    """Subprocess: hardware-PRNG digest of the plane-sharded fused round
    (sharded_fused.assert_prng_invariant).  On the single-chip tunnel
    the all-equal assertion is trivial (one device) but the digest
    itself is the real hardware PRNG artifact; a multi-chip pod runs
    the same step and checks the zero-ICI same-stream invariant for
    real."""
    import jax
    import numpy as np

    from gossip_tpu.parallel.sharded_fused import (assert_prng_invariant,
                                                   make_plane_mesh)
    n_dev = len(jax.devices())
    mesh = make_plane_mesh(n_dev)
    d = assert_prng_invariant(128 * 64, mesh)
    print(json.dumps({"devices": n_dev,
                      "digests": np.asarray(d).tolist()}))
    return 0


def prng_invariant():
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    p = subprocess.run([sys.executable, os.path.abspath(__file__),
                        "--prng-body"],
                       capture_output=True, text=True, timeout=900,
                       cwd=REPO, env=env)
    if p.returncode != 0:
        raise RuntimeError((p.stderr or p.stdout)[-400:])
    return json.loads(p.stdout.strip().splitlines()[-1])


def mr_staged_10m():
    # run-by-path puts tools/ (not the repo root) on the child's
    # sys.path; gossip_tpu needs an explicit PYTHONPATH entry
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    p = subprocess.run([sys.executable, os.path.abspath(__file__),
                        "--mr-body"],
                       capture_output=True, text=True, timeout=1200,
                       cwd=REPO, env=env)
    if p.returncode != 0:
        raise RuntimeError((p.stderr or p.stdout)[-400:])
    return json.loads(p.stdout.strip().splitlines()[-1])


def baseline_sweep():
    art = os.path.join(REPO, "artifacts", "baseline_sweep_r02b.jsonl")
    p = subprocess.run([sys.executable, "-m", "gossip_tpu", "sweep",
                        "--scale", "1.0"],
                       capture_output=True, text=True, timeout=2400,
                       cwd=REPO)
    if p.returncode != 0:
        raise RuntimeError(p.stderr[-400:])
    with open(art, "w") as f:
        f.write(p.stdout)
    rows = [json.loads(line) for line in p.stdout.splitlines()]
    return [{"config": r["config"], "rounds": r["rounds"],
             "coverage": round(r["coverage"], 4), "wall_s": r["wall_s"],
             "compile_s": r.get("meta", {}).get("compile_s"),
             "steady_wall_s": r.get("meta", {}).get("steady_wall_s"),
             "engine": r.get("meta", {}).get("engine")}
            for r in rows]


def bench():
    # must outlast bench.py's own worst case (probe retries + body +
    # hermetic retry) — computed by bench.py itself from the same
    # constants its loops use, so the budget can't drift
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(REPO, "bench.py"))
    bench_mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench_mod)
    budget = bench_mod.worst_case_budget_s() + 200
    p = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                       capture_output=True, text=True, timeout=budget,
                       cwd=REPO)
    if p.returncode != 0:
        raise RuntimeError((p.stderr or p.stdout)[-400:])
    return json.loads(p.stdout.strip().splitlines()[-1])


def tpu_pallas_tests():
    art = os.path.join(REPO, "artifacts", "tpu_pallas_tests_r02b.txt")
    # conftest pins tests to CPU unless this var points at the chip
    env = {**os.environ, "GOSSIP_TPU_TEST_PLATFORM": "axon"}
    p = subprocess.run([sys.executable, "-m", "pytest",
                        "tests/test_pallas.py", "tests/test_pallas_round.py",
                        "-q"],
                       capture_output=True, text=True, timeout=2400,
                       cwd=REPO, env=env)
    with open(art, "w") as f:
        f.write(p.stdout + "\n--- stderr ---\n" + p.stderr[-2000:])
    tail = p.stdout.strip().splitlines()[-1] if p.stdout.strip() else ""
    if p.returncode != 0:
        raise RuntimeError(tail)
    return tail


def main():
    step("mr_staged_10m", mr_staged_10m)
    step("prng_invariant", prng_invariant)
    step("baseline_sweep", baseline_sweep)
    step("bench", bench)
    step("tpu_pallas_tests", tpu_pallas_tests)
    return 0


if __name__ == "__main__":
    if "--mr-body" in sys.argv:
        sys.exit(_mr_staged_body())
    if "--prng-body" in sys.argv:
        sys.exit(_prng_body())
    sys.exit(main())
