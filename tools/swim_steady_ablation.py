#!/usr/bin/env python
"""Decompose SWIM-1M's STEADY-STATE ms/round on the chip (VERDICT r4 #4).

The r04 captures left SWIM's steady state at ~374 ms/round (sort
lowering, 1M nodes) with two named suspects — the dissemination reduce
and the 5-per-node threefry draws — but no runtime decomposition: the
r04 ablation (tools/swim_compile_ablation.py) decomposed COMPILE time
only.  This is its steady-state twin: the same stub-one-component-
at-a-time scheme (stubs keep all shapes/dtypes), but measuring executed
ms/round via a timed fori_loop chain instead of AOT compile seconds:

  full        the real step (sort dissemination)
  no_probe    probe_draws -> constant zeros (the per-node threefry
              probe/proxy chain: is it the lever PERF.md guesses?)
  no_diss     disseminate_max -> zeros (sort + gather + segment-max)
  no_sample   sample_peers -> static ring (table gather + partner draw)
  pack        swim_diss='pack' (the 8-bit transport-code gather)
  scatter     swim_diss='scatter' control

The deltas vs ``full`` are the decomposition; their sum vs ``full``
says how much is unattributed (fused overlap / everything-else).  The
artifact is the "measured floor statement" VERDICT r4 task 4 accepts if
no fix reaches steady < 10 s: whichever component dominates is the
floor's name.  Writes artifacts/swim_steady_ablation_r05.json
(merging variant rows across retries — a window that closes mid-run
keeps the measured variants).

Run only when the tunnel is healthy (exit 2 = transient, the capture
convention).  ``--smoke`` rehearses at CPU scale (n=20k).
"""

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
try:
    from _timing import timed_chain  # noqa: E402
finally:
    sys.path.pop(0)

PROTO_KW = dict(mode="swim", fanout=2, swim_proxies=3, swim_subjects=8,
                swim_suspect_rounds=24)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=1_000_000)
    ap.add_argument("--rounds", type=int, default=10,
                    help="rounds per timed chain (x3 median)")
    ap.add_argument("--only", nargs="*", default=None)
    ap.add_argument("--smoke", action="store_true")
    a = ap.parse_args()
    if a.smoke:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
    n = 20_000 if a.smoke else a.n

    import jax
    import jax.numpy as jnp

    from gossip_tpu import topology
    from gossip_tpu.config import ProtocolConfig, TopologyConfig
    from gossip_tpu.models import swim as SW

    backend = jax.default_backend()
    print(f"backend: {backend}", file=sys.stderr)
    topo = topology.build(TopologyConfig(family="power_law", n=n, k=3,
                                         degree_cap=256))
    jax.block_until_ready((topo.nbrs, topo.deg))

    real_probe = SW.probe_draws
    real_diss = SW.disseminate_max
    real_sample = SW.sample_peers

    def stub_probe(rkey, gids, s_count, n_, proxies, drop_prob):
        m = len(gids)
        return (jnp.zeros((m,), jnp.int32), jnp.zeros((m,), jnp.bool_),
                jnp.zeros((m, proxies), jnp.int32),
                jnp.zeros((m, proxies), jnp.bool_),
                jnp.zeros((m, proxies), jnp.bool_))

    def stub_diss(targets, wire, num_rows, impl="sort", max_rounds=None):
        return jnp.zeros((num_rows, wire.shape[1]), jnp.int32)

    def stub_sample(key, ids, topo_, fanout, exclude_self=True,
                    local_nbrs=None, local_deg=None):
        # hash-scattered targets, NOT a ring: the dissemination sort's
        # cost downstream depends on its input order, and feeding it
        # already-sorted ring segments would charge part of the sort's
        # real cost to this stub (attribution leak).  A multiplicative
        # hash keeps the input as disordered as real draws while
        # removing the threefry + table-gather work being measured.
        h = (ids[:, None].astype(jnp.uint32) * jnp.uint32(2654435761)
             + jnp.arange(fanout, dtype=jnp.uint32)[None, :]
             * jnp.uint32(40503))
        return (h % jnp.uint32(n)).astype(jnp.int32)

    variants = [
        ("full", "sort", {}),
        ("no_probe", "sort", {"probe_draws": stub_probe}),
        ("no_diss", "sort", {"disseminate_max": stub_diss}),
        ("no_sample", "sort", {"sample_peers": stub_sample}),
        ("pack", "pack", {}),
        ("scatter", "scatter", {}),
        # the real candidate lever (ProtocolConfig.swim_rng='packed'):
        # one key chain + one multi-word draw per node instead of ~5
        # threefry streams — unlike the stubs above this is a SHIPPED
        # lowering, so its row is a measurement of an actual option
        ("packed_rng", "sort", {"swim_rng": "packed"}),
        ("packed_rng_pack", "pack", {"swim_rng": "packed"}),
    ]
    if a.only:
        variants = [v for v in variants
                    if v[0] in a.only or v[0] == "full"]

    art = os.path.join(REPO, "artifacts",
                       f"swim_steady_ablation_r05{'.smoke' if a.smoke else ''}"
                       ".json")
    try:
        with open(art) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        doc = {}
    merged = {r["variant"]: r for r in doc.get("rows", [])}

    rows = []
    for name, impl, patches in variants:
        if merged.get(name, {}).get("backend") == backend and not a.only:
            continue                       # measured in an earlier window
        rng = patches.pop("swim_rng", "split")
        proto = ProtocolConfig(swim_diss=impl, swim_rng=rng, **PROTO_KW)
        for attr, fn in patches.items():
            setattr(SW, attr, fn)
        try:
            step, tables = SW.make_swim_round(
                proto, n, dead_nodes=(1,), fail_round=2, topo=topo,
                tabled=True, max_rounds=80)
            st = SW.init_swim_state(n, proto.swim_subjects, seed=0)
            t0 = time.time()
            ms = timed_chain(lambda i, s: step(s, *tables), st,
                             a.rounds) * 1e3
            row = {"variant": name, "ms_per_round": round(ms, 2),
                   "compile_plus_measure_s": round(time.time() - t0, 1),
                   "backend": backend}
        finally:
            SW.probe_draws = real_probe
            SW.disseminate_max = real_diss
            SW.sample_peers = real_sample
        print(json.dumps(row), flush=True)
        rows.append(row)
        merged[name] = row
        # persist after EVERY variant: a wedge mid-run keeps the rest
        full = merged.get("full")
        if full:
            for r in merged.values():
                r["delta_vs_full_ms"] = round(
                    r["ms_per_round"] - full["ms_per_round"], 2)
        from gossip_tpu.utils import telemetry
        doc = {"what": ("steady-state ms/round decomposition of the "
                        "BASELINE SWIM shape by component stubbing "
                        "(runtime twin of swim_compile_ablation); "
                        "negative delta = that component's steady "
                        "cost"),
               # the one artifact schema (tools/validate_artifacts.py)
               "provenance": telemetry.provenance(),
               "n": n, "proto": PROTO_KW, "rounds_timed": a.rounds,
               "rows": list(merged.values())}
        with open(art, "w") as f:
            json.dump(doc, f, indent=1)

    print(json.dumps({r["variant"]: r["ms_per_round"]
                      for r in merged.values()}), flush=True)
    print(f"wrote {art}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
