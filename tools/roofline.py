#!/usr/bin/env python
"""Roofline the fused kernels: is 74.6 ms the chip's floor? (VERDICT r4 #3)

The r04 capture proved the flagship 10M-node pull SI runs 2.87 ms/round
(fused value kernel) and the 10M x 32-rumor staged path 0.251 ms/round —
but nowhere stated what fraction of the chip those numbers are.  This
tool derives per-round floors from first principles, calibrates the
primitive rates ON THE CHIP, measures the actual kernels in the same
session, and writes artifacts/roofline_r05.json with utilization
fractions.

Methodology (stated honestly):

* The per-round work is counted from the kernel structure in
  ops/pallas_round.py (reference hot loop: /root/reference/main.go:72-88
  — the semantics contract; the counts are ours, not the reference's):

  - single-rumor value kernel (rows R = n_rows(n), fanout 1, all VMEM):
      prng_words = 8*128 + 32*R*128      (sbits + one draw per plane)
      gathers    = 32*R*128              (in-row dynamic_gather per plane)
      vpu_ops   ~= (3*ceil(log2 R) + 7*32 + 4) * R*128
  - staged big-MR path (rows M = mr_rows(n), table T = M*128*4 bytes):
      HBM floor traffic = 5*T  (XLA rotation: read T + write rot T;
      grid kernel: read table+rot 2T + write T).  If XLA instead
      materialized every roll stage the traffic would be
      (2*ceil(log2 M) + 3)*T — both floors are reported, and which one
      the measured number lands near ARBITRATES the PERF.md claim that
      the roll chain fuses to address arithmetic.

* Primitive rates are calibrated with Pallas microkernels at the SAME
  shapes the real kernel uses (draw count, gather count, op chain on
  [R, 128] uint32): prng_rate from a draw-only kernel, gather_rate
  differentially (draw+gather kernel minus the draw-only kernel, so the
  shared PRNG cost cancels), vpu_rate from an elementwise chain,
  hbm_rate from a streamed xor at the MR table size.

* Floors are reported two ways: ``serial_ms`` (sum of component times —
  exact if the units never overlap) and ``overlap_ms`` (max component —
  exact if they overlap perfectly).  The truth lies between; both are
  published so "utilization" can't be gamed by picking the flattering
  denominator.

Run at a healthy tunnel window (tools/tunnel_watchdog.py probes first;
hw_refresh runs this as its ``roofline`` step).  ``--smoke`` rehearses
the whole pipeline on the CPU interpreter at tiny shapes (the PRNG stub
returns zeros — plumbing rehearsal, not statistics).
"""

import argparse
import json
import math
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

LANES = 128
BITS = 32


# ---------------------------------------------------------------- counts

def single_rumor_counts(n: int) -> dict:
    """Per-round primitive counts for the single-rumor value kernel
    (ops/pallas_round._fused_round_kernel, fanout 1)."""
    from gossip_tpu.ops.pallas_round import n_rows
    rows = n_rows(n)
    words = rows * LANES
    stages = max(1, math.ceil(math.log2(rows)))
    return {
        "rows": rows,
        "table_bytes": words * 4,
        "prng_words": 8 * LANES + BITS * words,
        "gathers": BITS * words,
        # rotation: roll+cmp+select per stage; planes: ~7 elementwise
        # ops around each gather (index math, shift, and, or); +4 mask
        "vpu_ops": (3 * stages + 7 * BITS + 4) * words,
    }


def mr_staged_counts(n: int) -> dict:
    """Per-round traffic/counts for the staged big-MR path
    (ops/pallas_round._fused_mr_round_big)."""
    from gossip_tpu.ops.pallas_round import mr_rows
    rows = mr_rows(n)
    words = rows * LANES
    t_bytes = words * 4
    stages = max(1, math.ceil(math.log2(rows)))
    return {
        "rows": rows,
        "table_bytes": t_bytes,
        "roll_stages": stages,
        # fused rotation: read table + write rot; grid: read table+rot,
        # write out
        "hbm_bytes_fused_rot": 5 * t_bytes,
        # if every roll stage materialized instead
        "hbm_bytes_materialized_rot": (2 * stages + 3) * t_bytes,
        "prng_words": words,
        "gathers": words,
    }


# ---------------------------------------------------- timing scaffolding

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
try:
    from _timing import timed_chain as _timed_chain  # noqa: E402
finally:
    sys.path.pop(0)


def _microkernel(body, rows: int, interpret: bool):
    """Shared pallas_call plumbing for the calibration kernels: SMEM
    seed pair + VMEM table in/out (aliased), same as the real kernels'
    (ops/pallas_round._fused_call)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from gossip_tpu.compat import pallas_interpret_mode

    def call(i, table):
        seeds = jnp.stack([jnp.asarray(i, jnp.int32) * jnp.int32(1000003),
                           jnp.asarray(i, jnp.int32)])
        return pl.pallas_call(
            body,
            out_shape=jax.ShapeDtypeStruct((rows, LANES), jnp.uint32),
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                      pl.BlockSpec(memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            input_output_aliases={1: 0},
            interpret=pallas_interpret_mode(interpret),
        )(seeds, table)
    return call


def calibrate(rows: int, interpret: bool, iters: int) -> dict:
    """Primitive rates at the single-rumor kernel's shapes.  Returns
    words/s (prng), gathers/s, ops/s (vpu) — gather differentially so
    the PRNG cost the two kernels share cancels."""
    import jax.numpy as jnp
    from jax.experimental.pallas import tpu as pltpu

    words = rows * LANES

    def prng_body(seed_ref, tin_ref, tout_ref):
        pltpu.prng_seed(seed_ref[0], seed_ref[1])
        acc = tin_ref[:]
        for _ in range(BITS):
            acc = acc | pltpu.bitcast(
                pltpu.prng_random_bits((rows, LANES)), jnp.uint32)
        tout_ref[:] = acc

    def prng_gather_body(seed_ref, tin_ref, tout_ref):
        pltpu.prng_seed(seed_ref[0], seed_ref[1])
        table = tin_ref[:]
        acc = table
        for _ in range(BITS):
            rb = pltpu.bitcast(
                pltpu.prng_random_bits((rows, LANES)), jnp.uint32)
            m = (rb & jnp.uint32(LANES - 1)).astype(jnp.int32)
            acc = acc | jnp.take_along_axis(table, m, axis=1)
        tout_ref[:] = acc

    VPU_CHAIN = 256

    def vpu_body(seed_ref, tin_ref, tout_ref):
        acc = tin_ref[:]
        s = seed_ref[0].astype(jnp.uint32)
        for k in range(VPU_CHAIN):
            # alternating dependent ops, constants folded per k so the
            # chain cannot collapse
            acc = (acc ^ (s + jnp.uint32(k))) | (acc >> jnp.uint32(1))
        tout_ref[:] = acc

    init = jnp.zeros((rows, LANES), jnp.uint32)
    t_prng = _timed_chain(_microkernel(prng_body, rows, interpret),
                          init, iters)
    t_pg = _timed_chain(_microkernel(prng_gather_body, rows, interpret),
                        init, iters)
    t_vpu = _timed_chain(_microkernel(vpu_body, rows, interpret),
                         init, iters)
    # the differential only resolves the gather when the combined kernel
    # is measurably slower than draw-only; below ~5% of t_prng the
    # difference is timing noise (or fusion hid the gather entirely) and
    # an honest artifact must say "unresolved", not emit an impossible
    # 1e13 gathers/s that skews the floors
    t_gather = t_pg - t_prng
    resolved = t_gather > 0.05 * t_prng
    return {
        "shape": [rows, LANES],
        "prng_words_per_s": BITS * words / t_prng,
        "gathers_per_s": (BITS * words / t_gather) if resolved else None,
        "gather_resolved": resolved,
        # 3 elementary vector ops per step (xor, shift, or; the s+k
        # addend is scalar, folded per k) — matches the 3x multiplier
        "vpu_ops_per_s": 3 * VPU_CHAIN * words / t_vpu,
        "t_prng_ms": t_prng * 1e3,
        "t_prng_gather_ms": t_pg * 1e3,
        "t_vpu_ms": t_vpu * 1e3,
    }


def hbm_rate(table_bytes: int, iters: int) -> dict:
    """Streamed read+write rate at the MR table size (jitted xor chain:
    each step reads T and writes T)."""
    import jax
    import jax.numpy as jnp

    words = table_bytes // 4
    init = jnp.zeros((words,), jnp.uint32)

    def step(i, t):
        return t ^ (i.astype(jnp.uint32) | jnp.uint32(1))

    per_iter = _timed_chain(step, init, iters)
    return {"bytes_per_s": 2 * table_bytes / per_iter,
            "stream_ms_per_iter": per_iter * 1e3}


# ------------------------------------------------------------ actual runs

def measure_single(n: int, interpret: bool, rounds: int,
                   plane_sharing: int = 1) -> float:
    """Measured ms/round for the real single-rumor fused kernel
    (``plane_sharing=2``: the PRNG-harvest variant — half the draw
    words; measuring both arbitrates the harvest on-chip)."""
    from gossip_tpu.ops.pallas_round import (fused_pull_round,
                                             init_fused_state)
    st = init_fused_state(n)

    def step(i, table):
        return fused_pull_round(table, 0, i, n, 1, interpret,
                                plane_sharing=plane_sharing)

    return _timed_chain(step, st.table, rounds) * 1e3


def measure_mr_staged(n: int, rumors: int, interpret: bool,
                      rounds: int) -> float:
    """Measured ms/round for the real staged big-MR path."""
    from gossip_tpu.ops.pallas_round import (fused_multirumor_pull_round,
                                             init_multirumor_state)
    st = init_multirumor_state(n, rumors)

    def step(i, table):
        return fused_multirumor_pull_round(table, 0, i, n, 1, interpret)

    return _timed_chain(step, st.table, rounds) * 1e3


# ----------------------------------------------------------------- driver

def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=10_000_000)
    ap.add_argument("--rumors", type=int, default=32)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--smoke", action="store_true",
                    help="CPU interpreter rehearsal at tiny shapes")
    a = ap.parse_args()
    smoke = a.smoke
    if smoke:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        n, rumors, iters = 4096 * 8, 8, 2
    else:
        n, rumors, iters = a.n, a.rumors, a.iters

    import jax
    backend = jax.default_backend()

    sr = single_rumor_counts(n)
    mr = mr_staged_counts(n)

    cal = calibrate(sr["rows"], smoke, iters)
    hbm = hbm_rate(mr["table_bytes"], iters)

    actual_sr_ms = measure_single(n, smoke, iters)
    actual_sr2_ms = measure_single(n, smoke, iters, plane_sharing=2)
    actual_mr_ms = measure_mr_staged(n, rumors, smoke, iters)

    # component floors for the single-rumor kernel.  An unresolved
    # gather rate contributes 0 to the floor (a LOWER bound stays valid
    # — the true floor can only be higher) and is flagged so consumers
    # (tools/postcapture.py) don't present a skewed utilization as
    # doc-ready.
    prng_ms = sr["prng_words"] / cal["prng_words_per_s"] * 1e3
    gather_ms = (sr["gathers"] / cal["gathers_per_s"] * 1e3
                 if cal["gather_resolved"] else 0.0)
    vpu_ms = sr["vpu_ops"] / cal["vpu_ops_per_s"] * 1e3
    serial_ms = prng_ms + gather_ms + vpu_ms
    overlap_ms = max(prng_ms, gather_ms, vpu_ms)

    # HBM floors for the staged path
    mr_floor_fused = mr["hbm_bytes_fused_rot"] / hbm["bytes_per_s"] * 1e3
    mr_floor_mat = (mr["hbm_bytes_materialized_rot"]
                    / hbm["bytes_per_s"] * 1e3)

    from gossip_tpu.utils import telemetry
    doc = {
        "what": ("first-principles per-round floors vs measured actuals "
                 "for both fused layouts; primitive rates calibrated "
                 "on-chip this session (see module doc for the count "
                 "derivations)"),
        # the one artifact schema (run_id/git_commit/captured —
        # tools/validate_artifacts.py): floors are claims about a
        # commit and a toolchain, so they carry their attribution
        "provenance": telemetry.provenance(),
        "backend": backend,
        "smoke": smoke,
        "n": n,
        "rumors": rumors,
        "calibration": {**cal, "hbm": hbm},
        "single_rumor": {
            "counts": sr,
            "actual_ms_per_round": round(actual_sr_ms, 4),
            # the PRNG-harvest candidate (plane pairs split one draw;
            # opt-in different stream — ops/pallas_round docstring):
            # if this beats actual_ms and PRNG is the dominant floor
            # component, the harvest is proven on-chip
            "actual_ms_plane_sharing2": round(actual_sr2_ms, 4),
            "floor_components_ms": {"prng": round(prng_ms, 4),
                                    "gather": round(gather_ms, 4),
                                    "vpu": round(vpu_ms, 4)},
            "gather_floor_resolved": cal["gather_resolved"],
            "floor_serial_ms": round(serial_ms, 4),
            "floor_overlap_ms": round(overlap_ms, 4),
            "utilization_vs_serial": round(serial_ms / actual_sr_ms, 4),
            "utilization_vs_overlap": round(overlap_ms / actual_sr_ms, 4),
        },
        "mr_staged": {
            "counts": mr,
            "actual_ms_per_round": round(actual_mr_ms, 4),
            "floor_ms_fused_rotation": round(mr_floor_fused, 4),
            "floor_ms_materialized_rotation": round(mr_floor_mat, 4),
            "utilization_vs_fused_floor": round(
                mr_floor_fused / actual_mr_ms, 4),
            "rotation_fuses": bool(actual_mr_ms < mr_floor_mat / 2),
        },
    }
    infix = ".smoke" if smoke else ""
    art = os.path.join(REPO, "artifacts", f"roofline_r05{infix}.json")
    with open(art, "w") as f:
        json.dump(doc, f, indent=1)
    print(json.dumps({"single_actual_ms": doc["single_rumor"]
                      ["actual_ms_per_round"],
                      "single_util_serial": doc["single_rumor"]
                      ["utilization_vs_serial"],
                      "mr_actual_ms": doc["mr_staged"]
                      ["actual_ms_per_round"],
                      "mr_util_hbm": doc["mr_staged"]
                      ["utilization_vs_fused_floor"],
                      "backend": backend, "smoke": smoke}))
    print(f"wrote {art}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
