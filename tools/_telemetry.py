"""Single-source loader for ``gossip_tpu.utils.telemetry`` from tools/
scripts (which run by path with tools/, not the repo root, on
sys.path) — the same one-definition pattern as tools/_bench.py, so the
ledger-bootstrap idiom cannot drift between hw_refresh and the
watchdog."""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def telemetry():
    sys.path.insert(0, REPO)
    try:
        from gossip_tpu.utils import telemetry as mod
    finally:
        sys.path.pop(0)
    return mod


def open_ledger(default_path):
    """telemetry.from_env with the tool's default path — never raises
    (from_env degrades to Null/EchoLedger on an unwritable path)."""
    return telemetry().from_env(default_path=default_path)
