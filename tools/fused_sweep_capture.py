#!/usr/bin/env python
"""Capture the compile-amortized FUSED churn-sweep record (the
fused-operand PR's acceptance artifact).

Two legs over the SAME K mixed nemesis scenarios on the plane-sharded
fused engine (parallel/sharded_fused.simulate_curve_sharded_fused):

  * ``solo`` — K reruns, each forced through a fresh trace + XLA
    compile (the memoized fused loop, the cached mask builders, and
    jax's in-memory caches are cleared between scenarios, and the
    persistent compile cache is suspended) — the pre-PR cost model,
    where the drop threshold was a compile-time kernel static and
    every fused fault scenario paid a full recompile (and partitions/
    ramps could not run at all);
  * ``warm`` — the same K scenarios through the ONE memoized compiled
    loop (parallel/sweep.fused_churn_sweep_curves: alive words, cut
    masks, and the threshold table behind the SMEM scalar are all
    runtime operands): scenario 1 pays the only compile (reported
    separately as ``compile_ms``), scenarios 2..K are in-memory
    executable reuses, and a SALTED family re-enters with zero
    compiles.  The acceptance line is
    ``solo_total_ms >= 3 * warm_total_ms``.

Everything lands in ONE run ledger (utils/telemetry — provenance first
line), so the committed artifact passes tools/validate_artifacts.py's
fused-sweep provenance gate.

    python tools/fused_sweep_capture.py [OUT.jsonl]   # default
        artifacts/ledger_fused_sweep_r17.jsonl
    python tools/fused_sweep_capture.py --smoke       # CPU rehearsal,
        .smoke-infixed artifact (the hw_refresh rehearsal convention)

Platform: the tool keeps the AMBIENT jax platform — on a TPU window
(the tools/hw_refresh.py ``fused_churn_sweep`` step) the kernels are
the real Mosaic lowerings and the solo leg pays true per-scenario
kernel recompiles; off-TPU (this container's committed record, and
``--smoke``) the kernels lower through the pure-JAX reference
interpret path, where the ratio is a compile-vs-reuse STRUCTURE and
strictly conservative (a Mosaic kernel compile is heavier than the
reference lowering's XLA compile).  The backend and lowering are
recorded in the ledger line either way.
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

K = 8
N = 128 * 8
RUMORS = 64
DEVICES = 4
MAX_ROUNDS = 8


def scenarios(salt=0):
    """K mixed fault programs — the ONE shared scenario-family
    generator (ops/nemesis.mixed_scenarios; the dry-run
    fused_churn_sweep family draws from it too)."""
    from gossip_tpu.ops import nemesis as NE
    return NE.mixed_scenarios(K, N, salt=salt, drop_prob=0.05, seed=2)


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    smoke = "--smoke" in argv
    argv = [a for a in argv if a != "--smoke"]
    infix = ".smoke" if smoke else ""
    out_path = (argv[0] if argv else
                os.path.join(REPO, "artifacts",
                             f"ledger_fused_sweep_r17{infix}.jsonl"))
    # hermetic: the persistent/AOT cache must not serve the solo leg
    os.environ["GOSSIP_COMPILE_CACHE"] = ""
    if smoke:
        os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={DEVICES}"
        ).strip()

    import jax
    from gossip_tpu.config import RunConfig
    from gossip_tpu.parallel import sharded_fused as SF
    from gossip_tpu.parallel.sweep import fused_churn_sweep_curves
    from gossip_tpu.utils import telemetry

    backend = jax.default_backend()
    interpret = backend != "tpu"
    run = RunConfig(seed=0, max_rounds=MAX_ROUNDS)
    mesh = SF.make_plane_mesh(DEVICES)
    faults = scenarios()

    led = telemetry.Ledger(out_path)
    prev = telemetry.activate(led)
    try:
        led.record_runtime()

        def clear():
            SF._cached_curve_scan.cache_clear()
            SF._cached_churn_masks.cache_clear()
            SF._cached_plane_init.cache_clear()
            jax.clear_caches()

        def one(fault):
            t0 = time.perf_counter()
            covs, _ = SF.simulate_curve_sharded_fused(
                N, RUMORS, run, mesh, fault=fault, interpret=interpret)
            return (time.perf_counter() - t0) * 1e3, covs

        # -- solo leg: every scenario pays trace + compile ------------
        solo_ms = []
        for i, f in enumerate(faults):
            clear()
            ms, covs = one(f)
            solo_ms.append(ms)
            led.event("fused_sweep_solo", scenario=i,
                      wall_ms=round(ms, 1),
                      final_coverage=round(float(covs[-1]), 6))

        # -- warm leg: one compile, K reuses --------------------------
        clear()
        t0 = time.perf_counter()
        one(faults[0])                      # the only compile
        compile_ms = (time.perf_counter() - t0) * 1e3
        t0 = time.perf_counter()
        res = fused_churn_sweep_curves(N, RUMORS, run, faults, mesh,
                                       interpret=interpret)
        warm_total = (time.perf_counter() - t0) * 1e3
        for i, s in enumerate(res.summaries()):
            led.event("fused_sweep_scenario", idx=i, **s)
        # salted re-entry: new schedule content, same shapes — the
        # zero-compile claim exercised end to end on fresh content
        t0 = time.perf_counter()
        fused_churn_sweep_curves(N, RUMORS, run, scenarios(salt=3),
                                 mesh, interpret=interpret)
        salted_ms = (time.perf_counter() - t0) * 1e3

        solo_total = sum(solo_ms)
        speedup = solo_total / max(warm_total, 1e-9)

        led.event("fused_sweep_record",
                  k=K, n=N, rumors=RUMORS, devices=DEVICES,
                  driver="fused_planes", max_rounds=MAX_ROUNDS,
                  backend=backend,
                  lowering="reference" if interpret else "mosaic",
                  smoke=smoke,
                  solo_total_ms=round(solo_total, 1),
                  warm_total_ms=round(warm_total, 1),
                  compile_ms=round(compile_ms, 1),
                  salted_reentry_ms=round(salted_ms, 1),
                  speedup=round(speedup, 2),
                  accept_3x=bool(solo_total >= 3 * warm_total))
        line = {"k": K, "backend": backend,
                "solo_total_ms": round(solo_total, 1),
                "warm_total_ms": round(warm_total, 1),
                "speedup": round(speedup, 2),
                "salted_reentry_ms": round(salted_ms, 1),
                "ledger": out_path}
        print(json.dumps(line))
        return 0 if solo_total >= 3 * warm_total else 1
    finally:
        telemetry.activate(prev)
        led.close()


if __name__ == "__main__":
    sys.exit(main())
