#!/usr/bin/env python
"""Run the round-4 ensemble surface on hardware (VERDICT r4 task 6).

The seed-axis ensembles (SWIM detection-latency distribution, SI
rounds-to-target quantiles) shipped in round 4 CPU-tested only.  This
tool drives the SAME public CLI path a user would
(``run --ensemble S``) on the chip, for:

  1. the BASELINE SWIM-1M shape, 16 seeds — detection-latency
     distribution of the failure detector, and
  2. the flagship SI pull shape at bench scale (10M nodes, XLA threefry
     engine — ensembles are contractually threefry: backend.run_ensemble
     rejects engine='fused'), 8 seeds — rounds-to-target quantiles.

Each sub-capture is its own CLI subprocess (own process group,
group-kill on timeout — the single-client-tunnel contract), and the
artifact is written after EVERY sub-capture, so a window that closes
mid-run keeps the completed half.  artifacts/ensembles_r05.json.

``--smoke`` rehearses both sub-captures at CPU scale hermetically.
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
try:
    from _bench import hermetic_cpu_env as _hermetic_cpu_env  # noqa: E402
finally:
    sys.path.pop(0)


def sub_captures(smoke: bool):
    """(name, cli_args, timeout_s) per sub-capture, priority order."""
    if smoke:
        swim_n, si_n, swim_seeds, si_seeds = 20_000, 100_000, 4, 4
    else:
        swim_n, si_n, swim_seeds, si_seeds = 1_000_000, 10_000_000, 16, 8
    return [
        ("swim_1m_detection", [
            "run", "--mode", "swim", "--n", str(swim_n),
            "--family", "power_law", "--k", "3", "--degree-cap", "256",
            "--fanout", "2", "--swim-subjects", "8", "--swim-proxies", "3",
            "--swim-suspect-rounds", "24", "--max-rounds", "80",
            "--ensemble", str(swim_seeds)], 1500),
        ("si_pull_bench_scale", [
            "run", "--mode", "pull", "--n", str(si_n), "--fanout", "1",
            "--max-rounds", "40", "--ensemble", str(si_seeds)], 900),
    ]


def run_capture(args, timeout_s: int, smoke: bool) -> dict:
    cmd = [sys.executable, "-u", "-m", "gossip_tpu", *args]
    env = _hermetic_cpu_env() if smoke else dict(os.environ)
    t0 = time.time()
    p = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                         stderr=subprocess.PIPE, text=True, cwd=REPO,
                         env=env, start_new_session=True)
    try:
        stdout, stderr = p.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(os.getpgid(p.pid), signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        p.communicate()
        raise
    if p.returncode != 0:
        raise RuntimeError(f"CLI rc={p.returncode}\n{stderr[-1500:]}")
    out = None
    for line in stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                cand = json.loads(line)
            except json.JSONDecodeError:
                continue
            if "ensemble" in cand:
                out = cand
    if out is None:
        raise RuntimeError(f"no ensemble JSON on stdout\n{stdout[-1500:]}")
    out["subprocess_wall_s"] = round(time.time() - t0, 1)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--only", nargs="*", default=None,
                    help="subset of sub-capture names")
    a = ap.parse_args()
    infix = ".smoke" if a.smoke else ""
    art = os.path.join(REPO, "artifacts", f"ensembles_r05{infix}.json")
    try:
        with open(art) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        doc = {"what": ("hardware capture of the seed-axis ensemble "
                        "surface via the public run --ensemble CLI "
                        "(VERDICT r4 task 6); sub-captures merge "
                        "incrementally — reruns only fill gaps")}

    timeouts = hard_failures = 0
    for name, args, timeout_s in sub_captures(a.smoke):
        if a.only is not None and name not in a.only:
            continue
        if doc.get(name, {}).get("ok"):
            continue                     # landed in an earlier window
        try:
            res = run_capture(args, timeout_s, a.smoke)
            doc[name] = {"ok": True, "command": " ".join(args),
                         "report": res}
        except subprocess.TimeoutExpired:
            timeouts += 1
            doc[name] = {"ok": False,
                         "error": f"timeout after {timeout_s} s "
                                  "(wedge signature)"}
        except Exception as e:
            hard_failures += 1
            doc[name] = {"ok": False,
                         "error": f"{type(e).__name__}: {e}"[:800]}
        # stamped per write: the merged artifact's attribution is the
        # run that last touched it (the one artifact schema —
        # tools/validate_artifacts.py / staticcheck writer gate)
        from _telemetry import telemetry
        doc["provenance"] = telemetry().provenance()
        with open(art, "w") as f:
            json.dump(doc, f, indent=1)
    # final summary line = the callers' machine-readable result
    # (tools/hw_refresh.py parses the LAST stdout JSON line)
    print(json.dumps({k: v.get("ok") for k, v in doc.items()
                      if isinstance(v, dict)}), flush=True)
    print(f"wrote {art}", file=sys.stderr)
    # exit codes follow the capture-tool convention (swim_diss_ab):
    # 2 = transient (a sub-capture hit the wedge signature; retry at
    # the next window fills the gap), 1 = deterministic failure
    if timeouts:
        return 2
    return 0 if hard_failures == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
